"""Fused plane x dp mesh (PR 17): the three dp=1 refusals are gone.

The load-bearing pins:

* **Fused dp=N == serial dp=N, bit-identical.**  On an emulated
  ``device_count=4`` CPU mesh (subprocess pytest — the PR 3 pattern),
  ``steps_per_dispatch=3 x 2`` dispatches equal ``steps_per_dispatch=1
  x 6``: params, opt_state, all per-shard replay trees (leading shard
  axis intact), the engine key chain, the sample key chain, and the
  device ingest counter.  The dp speedup claim rests on proven
  identical work.
* **Replay-service batches train under dp>1** — the batch shards over
  the mesh, the update pmeans, and every shard write-back routes with
  the idx alignment unchanged (the PR 7 guard is gone).
* **Tenant partitions ride the same path** — a tenant-qualified learner
  (APEX_TENANT) trains on service batches at dp=2 (the PR 13 guard fell
  transitively with the service guard).
* **Live train_ratio** (the PR 15 carried knob): the device budget
  throttles fused train steps to ``ingested * ratio / batch`` at every
  dp width, and the no-ratio program is untouched.
"""

from __future__ import annotations

import dataclasses
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

pytest.importorskip("jax")
import jax  # noqa: E402

from apex_tpu.config import (ActorConfig, ApexConfig,  # noqa: E402
                             EnvConfig, LearnerConfig, ReplayConfig,
                             small_test_config)
from apex_tpu.ondevice.fused import FusedApexTrainer  # noqa: E402

REPLAY_FIELDS = ("frames", "action", "reward", "discount", "obs_ids",
                 "next_ids", "frame_epoch", "sum_tree", "min_tree",
                 "pos", "f_epoch", "size", "max_priority")

_INNER_ENV = "APEX_FUSED_DP_INNER"


def _cfg(dp=4, n_envs=4, warmup=32):
    return ApexConfig(
        env=EnvConfig(env_id="ApexCatchSmall-v0", frame_stack=2,
                      clip_rewards=False, episodic_life=False),
        replay=ReplayConfig(capacity=512, warmup=warmup,
                            beta_anneal=2000),
        learner=LearnerConfig(batch_size=16, compute_dtype="float32",
                              target_update_interval=50,
                              publish_interval=5, mesh_shape=(dp,)),
        actor=ActorConfig(n_actors=1, n_envs_per_actor=n_envs,
                          send_interval=8))


def _run_fused_dp(steps_per_dispatch, dispatches, dp=4, train_ratio=None):
    t = FusedApexTrainer(_cfg(dp=dp), rollout_len=8,
                         steps_per_dispatch=steps_per_dispatch,
                         train_ratio=train_ratio)
    for _ in range(dispatches):
        t.train_state, t.replay_state, t.key, info = t.fused.dispatch(
            t.train_state, t.replay_state, t.key)
    return t


# -- fused dp=N vs serial dp=N (acceptance pin, subprocess) -----------------

@pytest.mark.skipif(os.environ.get(_INNER_ENV) != "1",
                    reason="spawned by test_fused_dp4_vs_serial_bit_"
                           "parity on a 4-device mesh")
def test_fused_dp4_parity_inner():
    """Inside the subprocess pytest: fused dp=4 scan composition is pure
    dispatch amortization — same macro body, same pre-split fan-out key
    chains — so 3x2 and 1x6 give bit-identical everything."""
    assert jax.device_count() == 4

    a = _run_fused_dp(3, 2)
    b = _run_fused_dp(1, 6)

    pa = jax.tree.leaves(jax.device_get(
        (a.train_state.params, a.train_state.opt_state)))
    pb = jax.tree.leaves(jax.device_get(
        (b.train_state.params, b.train_state.opt_state)))
    assert pa and all(np.array_equal(np.asarray(x), np.asarray(y))
                      for x, y in zip(pa, pb))
    assert int(a.train_state.step) == int(b.train_state.step) > 0

    # per-shard replay trees: leading axis = the 4 pool partitions
    ra = jax.device_get(a.replay_state)
    rb = jax.device_get(b.replay_state)
    for name in REPLAY_FIELDS:
        va = np.asarray(getattr(ra, name))
        vb = np.asarray(getattr(rb, name))
        assert va.shape[0] == 4, f"replay field {name} lost its shard axis"
        assert np.array_equal(va, vb), f"replay field {name} diverged"
    # every chip's partition actually ingested
    assert (np.asarray(jax.device_get(a.replay_state.size)) > 0).all()

    # both host key chains advanced with the serial split discipline
    assert np.array_equal(
        np.asarray(jax.random.key_data(a.key)),
        np.asarray(jax.random.key_data(b.key)))
    assert np.array_equal(
        np.asarray(jax.random.key_data(a.fused.engine.key)),
        np.asarray(jax.random.key_data(b.fused.engine.key)))
    assert int(a.fused.ingested_dev) == int(b.fused.ingested_dev) > 0
    assert a.fused.train_steps == b.fused.train_steps > 0
    assert a.fused.prio_writebacks == b.fused.prio_writebacks > 0


def test_fused_dp4_vs_serial_bit_parity():
    """Acceptance pin, tier-1-safe: spawn the inner parity test in a
    fresh pytest on a CPU backend forced to exactly 4 devices (the
    emulation geometry the issue names)."""
    env = dict(os.environ)
    env[_INNER_ENV] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("PYTEST_CURRENT_TEST", None)
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", os.path.abspath(__file__), "-q",
         "-k", "test_fused_dp4_parity_inner", "-p", "no:cacheprovider"],
        capture_output=True, text=True, timeout=560, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    # rc 0 = collected AND passed (empty collection exits 5, failure 1)
    assert proc.returncode == 0, \
        f"inner fused dp=4 parity pytest failed:\n" \
        f"{proc.stdout}\n{proc.stderr}"


# -- live train_ratio (device budget) ---------------------------------------

def test_fused_train_ratio_throttles_on_device():
    """ratio=0.2 with batch=16 (well under this geometry's structural
    rate): steps stay within one step of ``ingested * ratio / batch``
    (the budget gate closes the moment consumption catches the accrual),
    and the unthrottled twin trains strictly more."""
    throttled = _run_fused_dp(2, 8, dp=2, train_ratio=0.2)
    free = _run_fused_dp(2, 8, dp=2)
    ing = throttled.fused.transitions
    cap = ing * 0.2 / 16
    assert throttled.fused.train_steps <= cap + 1
    assert throttled.fused.train_steps > 0
    assert free.fused.train_steps > throttled.fused.train_steps
    # the budget ledger is exact f32 arithmetic off the psum'd ingest
    assert float(throttled.fused.budget_dev) == pytest.approx(
        ing * 0.2 - throttled.fused.train_steps * 16)
    # no-ratio runs never touch the budget scalar
    assert float(free.fused.budget_dev) == 0.0


def test_fused_dp_counters_and_summary_shards():
    t = _run_fused_dp(2, 3, dp=2)
    c = t.fused.counters()
    assert c["dp"] == 2
    assert c["train_steps"] > 0 and c["prio_writebacks"] > 0
    sizes = np.asarray(jax.device_get(t.replay_state.size)).reshape(-1)
    assert sizes.shape == (2,) and (sizes > 0).all()


# -- replay service under dp>1 (PR 7 guard removal) -------------------------

class _StubPool:
    """No-chunk pool: the trainer must train on SERVICE batches alone."""

    procs: list = []

    def start(self):
        pass

    def cleanup(self):
        pass

    def poll_chunks(self, n, timeout=0.0):
        if timeout:
            time.sleep(min(timeout, 0.005))
        return []

    def poll_stats(self):
        return []

    def publish_params(self, version, params):
        pass


class _StubClient:
    """Serves pre-fabricated batches with the client's interface; records
    the write-backs the trainer routes back."""

    def __init__(self, batches):
        self._lock = threading.Lock()
        self._batches = list(batches)
        self.n_shards = 2
        self.batches = 0
        self.prio = []                   # (shard, seq) routed back
        self.rejected = self.prio_sent = self.prio_dropped = 0
        self.learner_epoch = 0

    def poll_batch(self, timeout=0.0):
        with self._lock:
            if not self._batches:
                return None
            self.batches += 1
            return self._batches.pop(0)

    def push_priorities(self, shard, seq, idx, priorities):
        assert np.asarray(priorities).dtype == np.float32
        assert np.asarray(priorities).shape == np.asarray(idx).shape
        with self._lock:
            self.prio.append((int(shard), int(seq)))
            self.prio_sent += 1
        return True

    def ingested_total(self):
        return 4096                      # "the shard fleet is warm"

    def shard_status(self):
        return []

    def close(self):
        pass


BATCH = 16


def _service_batches(cfg, count):
    from apex_tpu.training.apex import dqn_env_specs
    _, frame_shape, frame_dtype, frame_stack = dqn_env_specs(cfg)
    stacked = frame_shape[:-1] + (frame_stack * frame_shape[-1],)
    rng = np.random.default_rng(0)

    def obs():
        if np.dtype(frame_dtype) == np.uint8:
            return rng.integers(0, 255, (BATCH,) + stacked, np.uint8)
        return rng.normal(size=(BATCH,) + stacked).astype(frame_dtype)

    return [{
        "batch": {
            "obs": obs(),
            "action": rng.integers(0, 2, BATCH).astype(np.int32),
            "reward": rng.normal(size=BATCH).astype(np.float32),
            "next_obs": obs(),
            "discount": np.full(BATCH, 0.97, np.float32),
        },
        "weights": np.ones(BATCH, np.float32),
        "idx": rng.integers(0, 256, BATCH).astype(np.int32),
        "seq": i // 2, "shard": i % 2, "ingested": 2048,
    } for i in range(count)]


def _service_cfg():
    cfg = small_test_config(capacity=256, batch_size=BATCH)
    return cfg.replace(learner=dataclasses.replace(
        cfg.learner, mesh_shape=(2,)))


def test_service_batches_train_on_dp2_mesh():
    """The PR 7 refusal is gone: a dp=2 learner trains on shard-served
    batches through the shard_map'd batch-train (pmean'd update,
    priorities reassembled in sample order) and routes every write-back
    to its owning shard."""
    from apex_tpu.training.apex import ApexTrainer

    cfg = _service_cfg()
    client = _StubClient(_service_batches(cfg, 4))
    trainer = ApexTrainer(cfg, pool=_StubPool(), respawn_workers=False)
    assert trainer.n_dp == 2
    trainer.replay_client = client
    p_before = np.asarray(jax.device_get(
        jax.tree.leaves(trainer.train_state.params)[0])).copy()
    trainer.train(total_steps=4, max_seconds=120, log_every=10 ** 9)

    assert trainer.service_steps == 4
    assert sorted(client.prio) == [(0, 0), (0, 1), (1, 0), (1, 1)]
    p_after = np.asarray(jax.device_get(
        jax.tree.leaves(trainer.train_state.params)[0]))
    assert not np.array_equal(p_before, p_after)
    svc = trainer.fleet_summary()["metrics"]["replay_service"]
    assert svc["service_steps"] == 4 and svc["batches_pulled"] == 4


def test_tenant_partition_trains_on_dp2_mesh(monkeypatch):
    """The PR 13 refusal fell with the service guard: a tenant-qualified
    learner (APEX_TENANT) pulls its partition's batches and trains on
    the dp=2 mesh like any other service learner."""
    from apex_tpu.tenancy import namespace
    from apex_tpu.training.apex import ApexTrainer

    monkeypatch.setenv("APEX_TENANT", "rally")
    assert namespace.current_tenant() == "rally"
    cfg = _service_cfg()
    client = _StubClient(_service_batches(cfg, 2))
    trainer = ApexTrainer(cfg, pool=_StubPool(), respawn_workers=False)
    trainer.replay_client = client
    trainer.train(total_steps=2, max_seconds=120, log_every=10 ** 9)
    assert trainer.service_steps == 2
    assert client.prio_sent == 2


def test_batch_train_priorities_are_per_chip_blocks():
    """idx-alignment pin: the dp=2 shard_map'd batch-train reassembles
    ``[batch]`` as contiguous per-chip blocks, and each block equals the
    single-chip update body run on that half alone (priorities blend a
    per-BATCH max — ``mixed_max_priorities`` — so the per-chip
    normalizer is the established ShardedLearner semantics, not a
    global one)."""
    from apex_tpu.training.apex import ApexTrainer

    item = _service_batches(_service_cfg(), 1)[0]
    cfg = _service_cfg()
    tr = ApexTrainer(cfg, pool=_StubPool(), respawn_workers=False)
    fn = tr._make_batch_train()
    ts, prios, metrics = fn(tr.train_state, item["batch"],
                            item["weights"])
    p2 = np.asarray(jax.device_get(prios))
    assert p2.shape == (BATCH,)
    assert np.isfinite(float(metrics["loss"]))

    # reference: the plain update body on each contiguous half
    half = BATCH // 2
    ref = ApexTrainer(small_test_config(capacity=256, batch_size=BATCH),
                      pool=_StubPool(), respawn_workers=False)
    step = jax.jit(ref.core.update_from_batch)
    for c in range(2):
        sl = slice(c * half, (c + 1) * half)
        hb = {k: v[sl] for k, v in item["batch"].items()}
        _, p_half, _ = step(ref.train_state, hb, item["weights"][sl])
        np.testing.assert_allclose(p2[sl], np.asarray(p_half),
                                   rtol=1e-5)
