"""Model shape/semantics tests: dueling aggregation, policy fn, NoisyDense."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models.dueling import DuelingDQN, make_policy_fn
from apex_tpu.models.noisy import NoisyDense


def test_dueling_conv_shapes_and_identifiability(key):
    model = DuelingDQN(num_actions=6, compute_dtype=jnp.float32)
    obs = jnp.zeros((2, 84, 84, 4), jnp.uint8)
    params = model.init(key, obs)
    q = model.apply(params, obs)
    assert q.shape == (2, 6) and q.dtype == jnp.float32
    # conv trunk output matches Nature-DQN geometry: 7*7*64 flattened
    flat_in = params["params"]["advantage_hidden"]["kernel"].shape[0]
    assert flat_in == 7 * 7 * 64


def test_dueling_mlp_trunk(key):
    model = DuelingDQN(num_actions=2, obs_is_image=False,
                       compute_dtype=jnp.float32, scale_uint8=False)
    obs = jnp.ones((3, 4), jnp.float32)
    params = model.init(key, obs)
    assert model.apply(params, obs).shape == (3, 2)


def test_dueling_aggregation_mean_zero_advantage(key):
    """V + A - mean(A): per-row advantage contribution must be mean-zero."""
    model = DuelingDQN(num_actions=5, obs_is_image=False,
                       compute_dtype=jnp.float32, scale_uint8=False)
    obs = jax.random.normal(key, (4, 8))
    params = model.init(key, obs)
    q = model.apply(params, obs)
    # reconstruct value head output; q - value must be mean-zero per row
    centered = q - q.mean(axis=1, keepdims=True)
    np.testing.assert_allclose(np.asarray(centered.mean(axis=1)), 0.0,
                               atol=1e-5)


def test_policy_epsilon_extremes(key):
    model = DuelingDQN(num_actions=4, obs_is_image=False,
                       compute_dtype=jnp.float32, scale_uint8=False)
    obs = jax.random.normal(key, (64, 8))
    params = model.init(key, obs)
    policy = jax.jit(make_policy_fn(model))

    acts, q = policy(params, obs, jnp.float32(0.0), jax.random.key(1))
    np.testing.assert_array_equal(np.asarray(acts), np.asarray(q.argmax(1)))

    acts1, _ = policy(params, obs, jnp.float32(1.0), jax.random.key(2))
    acts2, _ = policy(params, obs, jnp.float32(1.0), jax.random.key(3))
    assert not np.array_equal(np.asarray(acts1), np.asarray(acts2))


def test_noisy_dense_noise_and_determinism(key):
    layer = NoisyDense(16)
    x = jnp.ones((2, 8))
    params = layer.init({"params": key, "noise": jax.random.key(1)}, x)

    y1 = layer.apply(params, x, rngs={"noise": jax.random.key(10)})
    y2 = layer.apply(params, x, rngs={"noise": jax.random.key(11)})
    y3 = layer.apply(params, x, rngs={"noise": jax.random.key(10)})
    assert not np.allclose(y1, y2)          # fresh noise differs
    np.testing.assert_allclose(y1, y3)      # same key reproduces

    det = NoisyDense(16, deterministic=True)
    d1 = det.apply(params, x)
    d2 = det.apply(params, x)
    np.testing.assert_allclose(d1, d2)      # eval mode: mu only, no rng needed

    # sigma init value matches reference: std_init/sqrt(fan_in)
    np.testing.assert_allclose(
        np.asarray(params["params"]["w_sigma"][0, 0]), 0.4 / np.sqrt(8),
        rtol=1e-6)
