"""Deploy template validation (C19 / VERDICT r3 item 8).

No terraform binary ships in this image, so ``terraform validate`` can't
run in CI; this is a structural checker over the HCL + bootstrap templates
that fails on the defect classes a broken edit would introduce: unbalanced
blocks, references to undeclared variables, template placeholders nobody
supplies, dangling resource references, and firewall ports drifting from
the CommsConfig defaults the roles actually bind (reference topology:
``origin_repo/deploy/deploy.tf``).
"""

import re
from pathlib import Path

DEPLOY = Path(__file__).resolve().parent.parent / "deploy"


def _strip_comments_and_strings(text: str) -> str:
    """Remove # comments; keep string contents (brace balance includes
    interpolation braces, which HCL nests legally)."""
    return re.sub(r"#[^\n]*", "", text)


def test_hcl_braces_and_quotes_balanced():
    for tf in sorted(DEPLOY.glob("*.tf")):
        text = _strip_comments_and_strings(tf.read_text())
        assert text.count("{") == text.count("}"), f"{tf.name}: brace count"
        assert text.count('"') % 2 == 0, f"{tf.name}: unbalanced quotes"


def _main_and_vars():
    main = (DEPLOY / "main.tf").read_text()
    variables = (DEPLOY / "variables.tf").read_text()
    declared = set(re.findall(r'variable\s+"(\w+)"', variables))
    referenced = set(re.findall(r"\bvar\.(\w+)", main))
    return main, declared, referenced


def test_variables_declared_and_used():
    _, declared, referenced = _main_and_vars()
    undeclared = referenced - declared
    assert not undeclared, f"main.tf references undeclared {undeclared}"
    unused = declared - referenced
    assert not unused, f"variables.tf declares unused {unused}"


def test_templatefile_references_and_placeholders():
    """Every templatefile() call points at an existing script, supplies
    every ``${name}`` placeholder the script uses, and passes no unused
    keys.  Bash's own ``$(...)``/``\\$x`` forms don't collide: only bare
    ``${identifier}`` is a terraform placeholder."""
    main = (DEPLOY / "main.tf").read_text()
    calls = re.findall(
        r'templatefile\("\$\{path\.module\}/([\w.]+)",\s*\{(.*?)\}\s*\)',
        main, re.DOTALL)
    assert len(calls) >= 3, "learner/actor/evaluator templates expected"
    for fname, body in calls:
        script = DEPLOY / fname
        assert script.exists(), f"templatefile target missing: {fname}"
        keys = set(re.findall(r"(\w+)\s*=", body))
        placeholders = set(re.findall(r"\$\{(\w+)\}", script.read_text()))
        missing = placeholders - keys
        assert not missing, f"{fname}: unsupplied placeholders {missing}"
        unused = keys - placeholders
        assert not unused, f"{fname}: keys passed but never used {unused}"


def test_resource_references_resolve():
    main, _, _ = _main_and_vars()
    defined = {f"{t}.{n}" for t, n in
               re.findall(r'resource\s+"(\w+)"\s+"(\w+)"', main)}
    for ref in re.findall(
            r"\b(google_[a-z0-9_]+\.\w+)\.", main):
        assert ref in defined, f"dangling resource reference {ref}"


def test_firewall_ports_match_comms_config():
    """The opened ports must be exactly what the roles bind: chunk ingest,
    param PUB, barrier (CommsConfig defaults) + tensorboard.  The
    reference additionally opened the replay server's 51002/51003
    (deploy.tf:64-126); those MUST be gone — the replay server is
    dissolved."""
    from apex_tpu.config import CommsConfig

    main = (DEPLOY / "main.tf").read_text()
    m = re.search(r'ports\s*=\s*\[([^\]]*)\]', main)
    assert m, "no firewall ports list"
    ports = {int(p) for p in re.findall(r'"(\d+)"', m.group(1))}
    c = CommsConfig()
    assert {c.batch_port, c.param_port, c.barrier_port} <= ports
    assert 6006 in ports                     # tensorboard
    assert c.prios_port not in ports and c.sample_port not in ports, \
        "replay-server ports resurrected — that server is dissolved"


def test_bootstrap_scripts_have_supervisor_loops():
    """Crashed remote roles must respawn (VERDICT r3 weak #6): the actor
    and evaluator bootstraps carry the rate-limited supervisor loop that
    pairs with roles.py's param-stream rejoin path."""
    for name in ("actor.sh", "evaluator.sh"):
        text = (DEPLOY / name).read_text()
        assert "while true" in text, f"{name}: no respawn loop"
        assert "sleep 5" in text, f"{name}: no respawn backoff"
        assert "fails" in text, f"{name}: no crash-loop rate limit"
