"""Deploy template validation (C19 / VERDICT r3 item 8).

No terraform binary ships in this image, so ``terraform validate`` can't
run in CI; this is a structural checker over the HCL + bootstrap templates
that fails on the defect classes a broken edit would introduce: unbalanced
blocks, references to undeclared variables, template placeholders nobody
supplies, dangling resource references, and firewall ports drifting from
the CommsConfig defaults the roles actually bind (reference topology:
``origin_repo/deploy/deploy.tf``).
"""

import re
from pathlib import Path

DEPLOY = Path(__file__).resolve().parent.parent / "deploy"


def _strip_comments_and_strings(text: str) -> str:
    """Remove # comments; keep string contents (brace balance includes
    interpolation braces, which HCL nests legally)."""
    return re.sub(r"#[^\n]*", "", text)


def test_hcl_braces_and_quotes_balanced():
    for tf in sorted(DEPLOY.glob("*.tf")):
        text = _strip_comments_and_strings(tf.read_text())
        assert text.count("{") == text.count("}"), f"{tf.name}: brace count"
        assert text.count('"') % 2 == 0, f"{tf.name}: unbalanced quotes"


def _main_and_vars():
    main = (DEPLOY / "main.tf").read_text()
    variables = (DEPLOY / "variables.tf").read_text()
    declared = set(re.findall(r'variable\s+"(\w+)"', variables))
    referenced = set(re.findall(r"\bvar\.(\w+)", main))
    return main, declared, referenced


def test_variables_declared_and_used():
    _, declared, referenced = _main_and_vars()
    undeclared = referenced - declared
    assert not undeclared, f"main.tf references undeclared {undeclared}"
    unused = declared - referenced
    assert not unused, f"variables.tf declares unused {unused}"


def test_templatefile_references_and_placeholders():
    """Every templatefile() call points at an existing script, supplies
    every ``${name}`` placeholder the script uses, and passes no unused
    keys.  Bash's own ``$(...)``/``\\$x`` forms don't collide: only bare
    ``${identifier}`` is a terraform placeholder."""
    main = (DEPLOY / "main.tf").read_text()
    calls = re.findall(
        r'templatefile\("\$\{path\.module\}/([\w.]+)",\s*\{(.*?)\}\s*\)',
        main, re.DOTALL)
    assert len(calls) >= 3, "learner/actor/evaluator templates expected"
    for fname, body in calls:
        script = DEPLOY / fname
        assert script.exists(), f"templatefile target missing: {fname}"
        keys = set(re.findall(r"(\w+)\s*=", body))
        placeholders = set(re.findall(r"\$\{(\w+)\}", script.read_text()))
        missing = placeholders - keys
        assert not missing, f"{fname}: unsupplied placeholders {missing}"
        unused = keys - placeholders
        assert not unused, f"{fname}: keys passed but never used {unused}"


def test_resource_references_resolve():
    main, _, _ = _main_and_vars()
    defined = {f"{t}.{n}" for t, n in
               re.findall(r'resource\s+"(\w+)"\s+"(\w+)"', main)}
    for ref in re.findall(
            r"\b(google_[a-z0-9_]+\.\w+)\.", main):
        assert ref in defined, f"dangling resource reference {ref}"


def test_firewall_ports_match_comms_config():
    """The opened ports must be exactly what the roles bind: chunk ingest,
    param PUB, barrier (CommsConfig defaults) + tensorboard.  The
    reference additionally opened the replay server's 51002/51003
    (deploy.tf:64-126); those MUST be gone — the replay server is
    dissolved."""
    from apex_tpu.config import CommsConfig

    main = (DEPLOY / "main.tf").read_text()
    m = re.search(r'ports\s*=\s*\[([^\]]*)\]', main)
    assert m, "no firewall ports list"
    ports = {int(p) for p in re.findall(r'"(\d+)"', m.group(1))}
    c = CommsConfig()
    assert {c.batch_port, c.param_port, c.barrier_port,
            c.status_port} <= ports
    assert 6006 in ports                     # tensorboard
    assert c.prios_port not in ports and c.sample_port not in ports, \
        "replay-server ports resurrected on the LEARNER — the learner " \
        "hosts no replay sockets (the sharded service has its own rule)"


def test_replay_firewall_range_matches_comms_config():
    """The replay-host rule must open shard s's port (replay_port_base +
    s) for every supported shard, with actors AND the learner as sources
    (chunks in, pulls/write-backs in) — and the shard heartbeat path back
    to the learner must include apex-replay as a source."""
    from apex_tpu.config import CommsConfig

    main = (DEPLOY / "main.tf").read_text()
    m = re.search(
        r'"apex_replay_ports"(.*?)target_tags\s*=\s*\[([^\]]*)\]',
        main, re.DOTALL)
    assert m, "no apex_replay_ports firewall resource"
    body, targets = m.group(1), m.group(2)
    r = re.search(r'"(\d+)-(\d+)"', body)
    assert r, "replay firewall opens no port range"
    lo, hi = int(r.group(1)), int(r.group(2))
    c = CommsConfig()
    assert lo == c.replay_port_base
    assert hi >= c.replay_port_base + 15     # 16 shards per host
    assert "apex-replay" in targets
    src = re.search(r'source_tags\s*=\s*\[([^\]]*)\]', body).group(1)
    assert "apex-actor" in src and "apex-learner" in src
    # heartbeat return path: shard beats ride the learner's chunk port
    learner_rule = re.search(
        r'"apex_ports"(.*?)target_tags\s*=\s*\[([^\]]*)\]',
        main, re.DOTALL).group(1)
    learner_src = re.search(r'source_tags\s*=\s*\[([^\]]*)\]',
                            learner_rule).group(1)
    assert "apex-replay" in learner_src


def test_infer_firewall_and_heartbeat_path_match_comms_config():
    """The infer-host rule must open the serving shard range anchored at
    CommsConfig.infer_port (shard s binds infer_port + s, 16 per host
    like replay) with actors AND the serve-ctl controller as sources —
    and the return paths to the learner (param SUB on 52001, heartbeats
    on the chunk port) must include apex-infer as a source."""
    from apex_tpu.config import CommsConfig

    main = (DEPLOY / "main.tf").read_text()
    m = re.search(
        r'"apex_infer_port"(.*?)target_tags\s*=\s*\[([^\]]*)\]',
        main, re.DOTALL)
    assert m, "no apex_infer_port firewall resource"
    body, targets = m.group(1), m.group(2)
    r = re.search(r'"(\d+)-(\d+)"', body)
    assert r, "infer firewall opens no shard port range"
    lo, hi = int(r.group(1)), int(r.group(2))
    assert lo == CommsConfig().infer_port
    assert hi >= CommsConfig().infer_port + 15   # 16 shards per host
    assert "apex-infer" in targets
    src = re.search(r'source_tags\s*=\s*\[([^\]]*)\]', body).group(1)
    assert "apex-actor" in src and "apex-serve-ctl" in src
    learner_rule = re.search(
        r'"apex_ports"(.*?)target_tags\s*=\s*\[([^\]]*)\]',
        main, re.DOTALL).group(1)
    learner_src = re.search(r'source_tags\s*=\s*\[([^\]]*)\]',
                            learner_rule).group(1)
    assert "apex-infer" in learner_src


def test_provisioning_is_pinned_and_idempotent():
    """The Packer-analogue (VERDICT r4 item 7; reference:
    origin_repo/deploy/packer/ape_x_cpu.sh): one parametrized provision
    script bakes a PINNED env at /opt/apex-env, short-circuits on its
    marker so baked images and first-boot paths share it, and covers both
    accelerator flavors."""
    text = (DEPLOY / "provision.sh").read_text()
    assert re.search(r'"jax\[tpu\]==[\d.]+"', text), "jax[tpu] not pinned"
    assert re.search(r'"jax==[\d.]+"', text), "cpu jax not pinned"
    for pkg in ("flax", "optax", "numpy", "pyzmq"):
        assert re.search(rf'"{pkg}==[\d.]+"', text), f"{pkg} not pinned"
    assert "python3 -m venv" in text
    assert "exit 0" in text and "MARKER" in text, "no idempotence marker"
    assert "build-essential" in text, "native shm ring needs a compiler"


def test_pyproject_dependencies_pinned_in_provision():
    """Closes the ``--no-deps`` drift hole (ADVICE): every
    ``[project].dependencies`` name from pyproject.toml must appear in
    provision.sh's pip pin list, or a new runtime dep would install in
    dev environments but silently be absent from every baked fleet
    image."""
    pyproject = DEPLOY.parent / "pyproject.toml"
    try:
        import tomllib
        deps = tomllib.loads(pyproject.read_text())["project"]["dependencies"]
    except ModuleNotFoundError:                      # pre-3.11 fallback
        m = re.search(r"dependencies\s*=\s*\[(.*?)\]",
                      pyproject.read_text(), re.DOTALL)
        assert m, "no [project].dependencies in pyproject.toml"
        deps = re.findall(r'"([^"]+)"', m.group(1))
    assert deps, "pyproject declares no dependencies?"

    text = (DEPLOY / "provision.sh").read_text()
    pin_lines = [ln for ln in text.splitlines() if '"' in ln
                 and ("pip install" in ln or ln.strip().startswith('"'))]
    pins = " ".join(pin_lines)
    for dep in deps:
        name = re.split(r"[<>=!~;\[\s]", dep.strip(), 1)[0]
        assert re.search(rf'"{re.escape(name)}(\[\w+\])?[=">]', pins), \
            f"pyproject dependency {name!r} missing from provision.sh's " \
            f"pip pin list — baked images would ship without it"


def test_role_scripts_use_baked_env():
    """Every role bootstrap must run through the provisioned interpreter
    (baked image or first-boot fallback) — an unpinned system python is
    exactly the version skew the bake exists to kill."""
    for name, flavor in (("actor.sh", "cpu"), ("evaluator.sh", "cpu"),
                         ("replay.sh", "cpu"), ("infer.sh", "cpu"),
                         ("learner.sh", "tpu")):
        text = (DEPLOY / name).read_text()
        assert f"provision.sh {flavor}" in text, \
            f"{name}: no first-boot provisioning fallback"
        assert f".provisioned-{flavor}" in text, \
            f"{name}: fallback not gated on the idempotence marker"
        assert "/opt/apex-env/bin/python" in text, \
            f"{name}: role not launched from the baked env"
        for m in re.finditer(r"\S*pip install", text):
            assert m.group(0).startswith("/opt/apex-env/bin/pip"), \
                f"{name}: ad-hoc pip install outside the baked env: " \
                f"{m.group(0)!r}"


def test_packer_template_structure():
    """deploy/packer/apex_images.pkr.hcl: balanced HCL, the build block
    consumes the declared source, and the file provisioner ships the
    provision script that actually exists."""
    pkr = DEPLOY / "packer" / "apex_images.pkr.hcl"
    text = _strip_comments_and_strings(pkr.read_text())
    assert text.count("{") == text.count("}"), "packer HCL brace count"
    srcs = re.findall(r'source\s+"googlecompute"\s+"(\w+)"', text)
    assert srcs, "no googlecompute source"
    for s in srcs:
        assert f"source.googlecompute.{s}" in text, f"source {s} unused"
    m = re.search(r'source\s*=\s*"\$\{path\.root\}/([./\w]+)"',
                  pkr.read_text())
    assert m, "file provisioner missing"
    assert (pkr.parent / m.group(1)).resolve().exists(), \
        f"provisioner ships missing file {m.group(1)}"
    assert "provision.sh cpu" in pkr.read_text()


def test_fleet_image_variable_wired():
    """The baked image is selectable per fleet node (fleet_image), and the
    TPU VM — which cannot boot custom images — still provisions via its
    startup script."""
    main, declared, _ = _main_and_vars()
    assert "fleet_image" in declared
    # actors + evaluator + replay host + infer host
    assert main.count("image = var.fleet_image") == 4


def test_validate_binaries_if_available():
    """Run the real validators when the binaries exist (they don't in this
    image — the structural checks above are the CI fallback)."""
    import shutil
    import subprocess

    if shutil.which("packer"):
        p = subprocess.run(["packer", "validate", "-syntax-only",
                            str(DEPLOY / "packer")],
                           capture_output=True, text=True)
        assert p.returncode == 0, p.stderr
    if shutil.which("terraform"):
        # validate needs the provider schema: init without any backend
        p = subprocess.run(["terraform", f"-chdir={DEPLOY}", "init",
                            "-backend=false", "-input=false"],
                           capture_output=True, text=True)
        assert p.returncode == 0, p.stderr
        p = subprocess.run(["terraform", f"-chdir={DEPLOY}", "validate"],
                           capture_output=True, text=True)
        assert p.returncode == 0, p.stderr


def test_bootstrap_scripts_use_host_supervisor():
    """Crashed remote roles must respawn (VERDICT r3 weak #6): the actor
    and evaluator bootstraps launch through the rate-limited,
    respawn-budgeted host supervisor (apex_tpu.fleet.supervise — the
    ActorPool respawn semantics for whole processes), which pairs with
    the roles' park/rejoin path.  The old inline ``while true`` loops
    must stay gone: they had no budget window and no jitter."""
    for name in ("actor.sh", "evaluator.sh", "replay.sh", "infer.sh"):
        text = (DEPLOY / name).read_text()
        assert "apex_tpu.fleet.supervise" in text, \
            f"{name}: role not launched under the host supervisor"
        assert "--max-respawns" in text and "--window" in text, \
            f"{name}: supervisor launched without a respawn budget"
        assert "/opt/apex-env/bin/python -m apex_tpu.fleet.supervise" \
            in text, f"{name}: supervisor not run from the baked env"
        assert "while true" not in text, \
            f"{name}: bare respawn loop resurrected alongside the " \
            f"supervisor"
