"""Jittable env ports (envs/jax_envs.py) — exact-trajectory parity.

The pin: stepped under identical seeds and actions, the JAX port of an env
produces the SAME trajectory — rendered uint8 observations, rewards,
terminations — as the numpy env in ``envs/toy.py``.  Catch is all-integer
dynamics, so parity is bitwise by construction; Rally's continuous state
runs in float32 on device, so the numpy reference is constructed with its
``dtype=np.float32`` knob and every op matches the port's correctly-rounded
IEEE-f32 op (the deflection lattice is non-dyadic — f64-vs-f32 trajectories
genuinely diverge at round()-to-pixel boundaries, which is why the knob
exists).

Randomness crosses the seam through :class:`KeyedNpRandom`: the ports draw
``jax.random`` values at fixed fold-in tags, and the shim replays the same
``(key, tag) -> value`` mapping into gymnasium's ``np_random`` surface.
Keyed draws are stateless, so draws one side makes and the other skips
(e.g. Rally's dead serve on the final point) can never desync the streams.
"""

from __future__ import annotations

import numpy as np
import pytest

pytest.importorskip("jax")
import jax  # noqa: E402

from apex_tpu.config import EnvConfig  # noqa: E402
from apex_tpu.envs import jax_envs, toy  # noqa: E402
from apex_tpu.envs.registry import (jittable_env, make_env,  # noqa: E402
                                    make_jax_env)


class KeyedNpRandom:
    """``np_random`` shim replaying the ports' keyed draws.  ``mode``
    selects the reset-scope vs step-scope tag family (the test driver
    flips it around ``env.reset()`` calls, mirroring the ports' in-step
    auto-reset tags)."""

    def __init__(self):
        self.key = None
        self.mode = "step"

    def _tag(self, step_tag: int, reset_tag: int) -> int:
        return reset_tag if self.mode == "reset" else step_tag

    def integers(self, low, high=None):
        lo, hi = (0, low) if high is None else (low, high)
        t = self._tag(jax_envs._T_INT, jax_envs._T_RESET_INT)
        return int(jax.random.randint(jax.random.fold_in(self.key, t),
                                      (), lo, hi))

    def random(self):
        t = self._tag(jax_envs._T_COIN, jax_envs._T_RESET_COIN)
        return float(jax.random.uniform(jax.random.fold_in(self.key, t)))

    def choice(self, arr):
        t = self._tag(jax_envs._T_CHOICE, jax_envs._T_RESET_CHOICE)
        i = int(jax.random.randint(jax.random.fold_in(self.key, t),
                                   (), 0, len(arr)))
        return arr[i]


def assert_trajectory_parity(np_env, jenv, steps: int, seed: int) -> int:
    """Drive both envs ``steps`` steps under one key chain + action stream;
    assert renders/rewards/dones equal bitwise at every step.  Returns the
    number of episode terminations seen (callers assert coverage)."""
    fake = KeyedNpRandom()
    np_env.reset(seed=0)             # materialize _np_random, then replace
    np_env._np_random = fake
    key = jax.random.key(seed)
    key, kr = jax.random.split(key)
    fake.key, fake.mode = kr, "reset"
    obs_np, _ = np_env.reset()
    st, obs_j = jenv.reset(kr)
    np.testing.assert_array_equal(obs_np, np.asarray(obs_j))
    step = jax.jit(jenv.step)
    rng = np.random.default_rng(seed)
    dones = 0
    for t in range(steps):
        a = int(rng.integers(0, 3))
        key, kt = jax.random.split(key)
        fake.key, fake.mode = kt, "step"
        obs_np, r_np, term, trunc, _ = np_env.step(a)
        st, obs_j, r_j, done_j, ff_j = step(st, np.int32(a), kt)
        done_np = bool(term or trunc)
        assert done_np == bool(done_j), f"done mismatch at step {t}"
        assert float(r_np) == float(np.asarray(r_j)), \
            f"reward mismatch at step {t}"
        # final_frame is the terminal render; obs the auto-reset render
        np.testing.assert_array_equal(obs_np, np.asarray(ff_j),
                                      err_msg=f"final frame, step {t}")
        if done_np:
            dones += 1
            fake.mode = "reset"
            obs_np, _ = np_env.reset()
        np.testing.assert_array_equal(obs_np, np.asarray(obs_j),
                                      err_msg=f"obs, step {t}")
    return dones


def test_catch_trajectory_parity_bitwise():
    dones = assert_trajectory_parity(toy.CatchEnv(),
                                     make_jax_env("ApexCatch-v0"),
                                     steps=250, seed=7)
    assert dones >= 1          # the pin covers termination + auto-reset


def test_catch_small_trajectory_parity_bitwise():
    dones = assert_trajectory_parity(
        toy.CatchEnv(grid=7, pixels=42, balls=3),
        make_jax_env("ApexCatchSmall-v0"), steps=200, seed=11)
    assert dones >= 5          # 18-step episodes: many resets covered


def test_rally_trajectory_parity():
    dones = assert_trajectory_parity(
        toy.RallyEnv(dtype=np.float32), make_jax_env("ApexRally-v0"),
        steps=400, seed=3)
    assert dones >= 1


def test_rally_small_trajectory_parity():
    # the Small certificate variant: wide agent paddle, 0.45-speed
    # opponent — exercises the non-integer opp_speed clip path
    assert_trajectory_parity(
        toy.RallyEnv(grid=14, pixels=42, points=2, agent_half=2,
                     opp_speed=0.45, dtype=np.float32),
        make_jax_env("ApexRallySmall-v0"), steps=400, seed=5)


def test_rally_default_dtype_unchanged():
    """The dtype knob's float64 default is bit-identical to the pre-knob
    python-float arithmetic — the calibrated certificate ladders keep
    their trajectories."""
    a, b = toy.RallyEnv(), toy.RallyEnv(dtype=np.float64)
    oa, _ = a.reset(seed=9)
    ob, _ = b.reset(seed=9)
    np.testing.assert_array_equal(oa, ob)
    for t in range(200):
        oa, ra, ta, tra, _ = a.step(t % 3)
        ob, rb, tb, trb, _ = b.step(t % 3)
        np.testing.assert_array_equal(oa, ob)
        assert ra == rb and ta == tb and tra == trb


def test_jittable_flag_and_geometry():
    assert jittable_env("ApexCatch-v0")
    assert jittable_env("ApexRallySmall-v0")
    assert not jittable_env("ApexCartPole-v0")
    assert not jittable_env("SeaquestNoFrameskip-v4")
    for env_id in ("ApexCatchSmall-v0", "ApexCatchMedium-v0",
                   "ApexRally-v0", "ApexRallySmall-v0"):
        jenv = make_jax_env(env_id)
        ref = make_env(env_id, EnvConfig(frame_stack=1), stack_frames=False)
        assert jenv.frame_shape == tuple(ref.observation_space.shape)
        assert jenv.num_actions == int(ref.action_space.n)
        ref.close()


def test_make_jax_env_rejects_non_jittable():
    with pytest.raises(ValueError, match="ApexCartPole-v0"):
        make_jax_env("ApexCartPole-v0")
    with pytest.raises(ValueError, match="ondevice"):
        make_jax_env("ApexContinuousNav-v0")


def test_scanned_batch_rollout_smoke():
    """The ports' raison d'être: vmapped env batches stepped under
    lax.scan in one jitted program, auto-reset keeping every lane live."""
    import jax.numpy as jnp

    env = make_jax_env("ApexCatchSmall-v0")
    B, T = 4, 40
    keys = jax.vmap(jax.random.fold_in, (None, 0))(
        jax.random.key(0), np.arange(B, dtype=np.uint32))
    states, obs = jax.vmap(env.reset)(keys)

    def body(carry, key):
        st, _ = carry
        acts = jax.random.randint(key, (B,), 0, env.num_actions)
        # apexlint: disable=J004 -- action draw vs per-slot env keys: randint(key) and fold_in(key, slot) are disjoint streams
        ks = jax.vmap(jax.random.fold_in, (None, 0))(
            key, jnp.arange(B, dtype=jnp.uint32))
        st, ob, r, d, _ff = jax.vmap(env.step)(st, acts, ks)
        return (st, ob), (r, d)

    @jax.jit
    def run(states, obs, key):
        return jax.lax.scan(body, (states, obs),
                            jax.random.split(key, T))

    (states, obs), (rewards, dones) = run(states, obs, jax.random.key(1))
    assert rewards.shape == (T, B) and dones.shape == (T, B)
    assert int(dones.sum()) >= B          # 18-step episodes: all lanes reset
    assert obs.shape == (B, 42, 42, 1) and obs.dtype == jnp.uint8
