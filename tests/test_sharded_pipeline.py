"""Sharded (dp>1) ingest pipeline: per-shard group-merge bit-parity, the
key-prefetcher chain contract, group-granular staging mechanics, and the
acceptance pin — dp=4 pipelined-vs-serial bit-parity of params AND
per-shard replay tree state on the same chunk stream, in a
subprocess-spawned pytest on a ``--xla_force_host_platform_device_count=4``
CPU mesh (``apex_tpu/training/ingest_pipeline.py`` sharded mode)."""

import copy
import dataclasses
import os
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from apex_tpu.actors.pool import drain_builder_chunks
from apex_tpu.config import small_test_config
from apex_tpu.parallel.aggregate import ChunkAggregator, stack_chunk_messages
from apex_tpu.replay.frame_chunks import FrameChunkBuilder
from apex_tpu.replay.frame_pool import FramePoolReplay
from apex_tpu.training.ingest_pipeline import (IngestPipeline, KeyPrefetcher,
                                               PipelineState,
                                               merge_group_messages)

# -- fixtures ---------------------------------------------------------------

K = 16          # transitions per worker chunk


def _cartpole_chunk_messages(n_chunks: int, seed: int = 0) -> list[dict]:
    """Chunks matching small_test_config's ApexCartPole spec — the exact
    payloads actor workers ship (same builder as tests/test_ingest_pipeline)."""
    rng = np.random.default_rng(seed)
    builder = FrameChunkBuilder(3, 0.99, 1, (4,), chunk_transitions=K,
                                frame_dtype=np.float32)
    msgs: list[dict] = []
    while len(msgs) < n_chunks:
        builder.begin_episode(rng.normal(size=4).astype(np.float32))
        ep_len = int(rng.integers(4, 40))
        for t in range(ep_len):
            builder.add_step(int(rng.integers(0, 2)), float(rng.normal()),
                             rng.normal(size=2).astype(np.float32),
                             rng.normal(size=4).astype(np.float32),
                             terminated=t == ep_len - 1, truncated=False)
        msgs.extend(drain_builder_chunks(builder))
    return msgs[:n_chunks]


def _group(msgs: list[dict]) -> dict:
    """One round-robin group message, exactly as ChunkAggregator stacks it."""
    payload, prios, n_trans = stack_chunk_messages(msgs)
    return {"payload": payload, "priorities": prios, "n_trans": n_trans}


class ScriptedPool:
    """Deterministic in-process chunk source with the pool interface."""

    def __init__(self, msgs):
        self._msgs = list(msgs)
        self.procs = []
        self.polled = 0
        self.published = []

    def start(self):
        pass

    def cleanup(self):
        pass

    def publish_params(self, version, params):
        self.published.append(version)

    def poll_stats(self):
        return []

    def poll_chunks(self, max_chunks, timeout=0.0):
        out = []
        while self._msgs and len(out) < max_chunks:
            out.append(self._msgs.pop(0))
        self.polled += len(out)
        return out


def _mini_sharded(n_dp: int):
    """A ShardedLearner stand-in exposing only what the pipeline's
    sharded mode touches host-side (n_dp; shard_put stays unused on the
    CPU backend, where put_device defaults off)."""
    from apex_tpu.parallel.learner import ShardedLearner
    from apex_tpu.parallel.mesh import make_mesh

    sl = ShardedLearner.__new__(ShardedLearner)
    object.__setattr__(sl, "core", None)
    object.__setattr__(sl, "mesh", make_mesh(dp=n_dp,
                                             devices=jax.devices()[:n_dp]))
    return sl


# -- per-shard group-merge bit-parity ---------------------------------------

@pytest.mark.parametrize("n_dp,m", [(2, 2), (4, 3), (4, 8)])
def test_merge_group_messages_bit_identical_per_shard(n_dp, m):
    """add(group_merge(g1..gm)) == add(g1); ...; add(gm) on EVERY state
    field of EVERY shard — through the real frame pool, so ref rebasing
    and epoch_off carry exactly as the single-shard merge contract."""
    msgs = _cartpole_chunk_messages(n_dp * m, seed=n_dp * 10 + m)
    groups = [_group(msgs[i * n_dp:(i + 1) * n_dp]) for i in range(m)]
    pool = FramePoolReplay(capacity=256, frame_shape=(4,), frame_stack=1,
                           frame_capacity=512, frame_dtype="float32")

    merged = merge_group_messages(copy.deepcopy(groups), n_dp)
    assert merged["n_trans"] == sum(g["n_trans"] for g in groups)

    for s in range(n_dp):
        seq = pool.init()
        for g in groups:
            seq = pool.add(
                seq, jax.tree.map(lambda x: x[s], g["payload"]),
                np.asarray(g["priorities"][s], np.float32))
        one = pool.add(
            pool.init(), jax.tree.map(lambda x: x[s], merged["payload"]),
            np.asarray(merged["priorities"][s], np.float32))
        for name in ("frames", "action", "reward", "discount", "obs_ids",
                     "next_ids", "frame_epoch", "sum_tree", "min_tree",
                     "pos", "f_epoch", "size", "max_priority"):
            va = np.asarray(getattr(seq, name))
            vb = np.asarray(getattr(one, name))
            assert np.array_equal(va, vb), \
                f"shard {s} state field {name} diverged"


def test_merge_group_messages_single_group_passthrough():
    g = _group(_cartpole_chunk_messages(4))
    assert merge_group_messages([g], 4) is g


# -- key prefetcher: the chain contract -------------------------------------

def test_key_prefetcher_matches_serial_split_chain():
    """take() i must yield EXACTLY device_keys(k_i) of the serial chain
    ``chain, k_i = split(chain)``, plus the chain state the inline split
    would have left behind — pipelined dispatch keys and post-train
    ``self.key`` both reduce to the serial sequence."""
    sl = _mini_sharded(4)
    seed = jax.random.key(42)
    pre = KeyPrefetcher(sl, seed, depth=3)
    pre.refill()

    chain = seed
    for i in range(7):              # crosses a refill boundary
        placed, after = pre.take()
        chain, k = jax.random.split(chain)
        np.testing.assert_array_equal(np.asarray(placed),
                                      np.asarray(sl.device_keys(k)))
        np.testing.assert_array_equal(np.asarray(jax.random.key_data(after)),
                                      np.asarray(jax.random.key_data(chain)))
        if i == 3:
            pre.refill()


# -- sharded staging mechanics ----------------------------------------------

def test_sharded_pipeline_groups_merge_and_preserve_order():
    """Through a real ChunkAggregator: ingest-only groups merge
    group-granular (dp axis intact, per-shard widths pow2-quantized),
    stream order is preserved, and totals balance."""
    n_dp = 4
    msgs = _cartpole_chunk_messages(n_dp * 8, seed=3)
    total = sum(int(m["n_trans"]) for m in msgs)
    pool = ChunkAggregator(ScriptedPool(msgs), n_dp)
    pipe = IngestPipeline(
        pool, depth=2, merge_max=4,
        state_fn=lambda: PipelineState(train_eligible=False),
        capacity=1 << 20, frame_capacity=1 << 20,
        sharded=_mini_sharded(n_dp))
    assert pipe.scan_steps == 1      # no scan stacking on the sharded plan
    pipe.start()
    try:
        slots = []
        for _ in range(40):
            slot = pipe.poll_slot(timeout=0.5)
            if slot is None:
                break
            slots.append(slot)
    finally:
        pipe.stop()
    assert sum(s.n_trans for s in slots) == total
    assert any(s.kind == "merged" for s in slots)
    for s in slots:
        # every slot keeps the dp axis in front, whatever its width
        assert np.asarray(s.payload["action"]).shape[0] == n_dp
        assert np.asarray(s.prios).shape[0] == n_dp
    # order: the concatenated per-shard action stream must equal the
    # source chunks round-robin-assigned in poll order
    for shard in range(n_dp):
        got = np.concatenate([
            np.asarray(s.payload["action"])[shard].reshape(-1)
            for s in slots])
        want = np.concatenate([
            np.asarray(m["payload"]["action"])
            for i, m in enumerate(_cartpole_chunk_messages(n_dp * 8, seed=3))
            if i % n_dp == shard])
        np.testing.assert_array_equal(got[:want.size], want)


def test_sharded_pipeline_behind_pauses_draining():
    n_dp = 4
    raw = ScriptedPool(_cartpole_chunk_messages(n_dp * 4, seed=5))
    pipe = IngestPipeline(
        ChunkAggregator(raw, n_dp), depth=2,
        state_fn=lambda: PipelineState(behind=True, train_eligible=False),
        sharded=_mini_sharded(n_dp))
    pipe.start()
    try:
        time.sleep(0.3)
        assert raw.polled == 0, "behind-learner must pause draining"
    finally:
        pipe.stop()


# -- the acceptance pin: dp=4 pipelined vs serial, bit for bit --------------

_INNER_ENV = "APEX_DP_PARITY_INNER"


def _run_dp_trainer(pipeline_on: bool, msgs, total_steps: int):
    from apex_tpu.training.apex import ApexTrainer

    cfg = small_test_config(capacity=256, batch_size=16, n_actors=1)
    cfg = cfg.replace(
        replay=dataclasses.replace(cfg.replay, warmup=256),
        learner=dataclasses.replace(cfg.learner, mesh_shape=(4,),
                                    ingest_pipeline=pipeline_on,
                                    target_update_interval=20))
    pool = ScriptedPool(copy.deepcopy(msgs))
    trainer = ApexTrainer(cfg, pool=pool, publish_min_seconds=10.0,
                          respawn_workers=False)
    trainer.train(total_steps=total_steps, max_seconds=300,
                  log_every=10 ** 9)
    return trainer


@pytest.mark.skipif(os.environ.get(_INNER_ENV) != "1",
                    reason="spawned by test_dp4_pipelined_vs_serial_"
                           "bit_parity on a 4-device mesh")
def test_dp4_parity_inner():
    """Runs inside the subprocess pytest: the SAME deterministic chunk
    stream through the dp=4 pipelined and serial trainer loops must give
    bit-identical params, per-shard replay tree state, AND post-train
    key chain.  The stream crosses the warmup boundary (merged
    round-robin groups), continues through staged trainable groups, and
    ends in replay-only catch-up steps (prefetched keys past the data)."""
    assert jax.device_count() == 4

    msgs = _cartpole_chunk_messages(80)      # 20 groups of 4 x 16 trans
    n = 30                                   # > post-warm group count
    t_serial = _run_dp_trainer(False, msgs, n)
    t_piped = _run_dp_trainer(True, msgs, n)

    assert t_serial.steps_rate.total == t_piped.steps_rate.total == n
    assert t_serial.ingested == t_piped.ingested == 80 * K

    ps = jax.device_get(t_serial.train_state.params)
    pp = jax.device_get(t_piped.train_state.params)
    flat_s = jax.tree_util.tree_leaves_with_path(ps)
    flat_p = dict(jax.tree_util.tree_leaves_with_path(pp))
    assert flat_s and len(flat_s) == len(flat_p)
    for path, leaf in flat_s:
        assert np.array_equal(np.asarray(leaf), np.asarray(flat_p[path])), \
            f"params diverged at {jax.tree_util.keystr(path)}"

    # per-shard replay trees: leading axis = the 4 shards
    for name in ("frames", "action", "reward", "discount", "obs_ids",
                 "next_ids", "frame_epoch", "sum_tree", "min_tree",
                 "pos", "f_epoch", "size", "max_priority"):
        va = np.asarray(getattr(t_serial.replay_state, name))
        vb = np.asarray(getattr(t_piped.replay_state, name))
        assert va.shape[0] == 4, f"replay field {name} lost its shard axis"
        assert np.array_equal(va, vb), f"replay field {name} diverged"

    # the key-prefetcher chain left self.key exactly where serial did
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(t_serial.key)),
        np.asarray(jax.random.key_data(t_piped.key)))

    # the pipelined run actually staged (merged warmup groups included)
    stats = t_piped._pipeline_last_stats
    assert stats is not None and stats["slots"] > 0
    assert stats["merged_chunks"] >= 2, \
        "warmup fill never exercised the sharded merged-group path"


def test_dp4_pipelined_vs_serial_bit_parity():
    """Acceptance pin, tier-1-safe: spawn the inner parity test in a
    fresh pytest on a CPU backend forced to exactly 4 devices — the
    sharded plan under the precise emulation geometry the issue names
    (XLA_FLAGS=--xla_force_host_platform_device_count=4)."""
    env = dict(os.environ)
    env[_INNER_ENV] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("PYTEST_CURRENT_TEST", None)
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", os.path.abspath(__file__), "-q",
         "-k", "test_dp4_parity_inner", "-p", "no:cacheprovider"],
        capture_output=True, text=True, timeout=560, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    # rc 0 = at least one test collected AND none failed (an empty
    # collection exits 5, a failure 1) — the inner run passed
    assert proc.returncode == 0, \
        f"inner dp=4 parity pytest failed:\n{proc.stdout}\n{proc.stderr}"
