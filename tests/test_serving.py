"""Sharded serving tier + epoch-fenced canary deployments (apex_tpu/serving).

Four contracts pinned here:

* the shard-routing hash (stable, uniform, computable anywhere);
* per-shard reply bit-parity vs local acting (each shard inherits PR 9's
  whole parity/fallback/re-probe story for its hashed worker band);
* the server-side version gate (pin holds installs, canary stashes the
  incumbent, rollback restores it BIT-IDENTICALLY, promote clears);
* the canary state machine under fake clocks and scripted SLO states
  (CANARY→PROMOTED on healthy soak, CANARY→ROLLED_BACK on breach,
  rejected versions never re-canaried), plus the deployment-timeline
  schema the CI serve-smoke drill asserts against.
"""

from __future__ import annotations

import socket
import threading
import time

import jax
import numpy as np
import pytest

from apex_tpu.actors.pool import actor_epsilons
from apex_tpu.actors.vector import VectorDQNWorkerFamily
from apex_tpu.config import CommsConfig, small_test_config
from apex_tpu.infer_service import InferServer
from apex_tpu.models.dueling import DuelingDQN, make_policy_fn
from apex_tpu.ops.losses import make_optimizer
from apex_tpu.runtime import wire
from apex_tpu.serving import fence
from apex_tpu.serving.deploy import (CANARY, IDLE, PROMOTED, ROLLED_BACK,
                                     DeployController, ServingStat,
                                     format_serving_lines,
                                     prometheus_sections)
from apex_tpu.serving.shard import infer_shard, make_infer_client, shard_port
from apex_tpu.training.apex import dqn_env_specs
from apex_tpu.training.state import create_train_state

SLO_OK = {"eval_score": "OK", "infer_rt_p99_ms": "OK"}


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _cfg(n_shards: int = 2, **comms_kw):
    cfg = small_test_config()
    return cfg.replace(comms=CommsConfig(infer_port=_free_port(),
                                         infer_shards=n_shards,
                                         **comms_kw))


def _params(cfg, model_spec, seed: int = 0):
    _, frame_shape, frame_dtype, frame_stack = dqn_env_specs(cfg)
    stacked = frame_shape[:-1] + (frame_stack * frame_shape[-1],)
    model = DuelingDQN(**model_spec)
    ts = create_train_state(model, make_optimizer(), jax.random.key(seed),
                            np.zeros((1,) + stacked, frame_dtype))
    return model, ts.params


def _serve(cfg, model, params, shard: int = 0, version=3, epoch=1):
    """A live shard server on its shard port, on a background thread."""
    server = InferServer(cfg.comms, make_policy_fn(model),
                         server_id=shard, heartbeat=False,
                         port=shard_port(cfg.comms, shard))
    if params is not None:
        server.set_params(version, params, epoch=epoch)
    stop = threading.Event()
    t = threading.Thread(target=server.run, kwargs={"stop_event": stop},
                         daemon=True)
    t.start()
    return server, stop, t


def _family(cfg, model_spec, n_envs):
    return VectorDQNWorkerFamily(
        cfg, model_spec, seeds=[100 + i for i in range(n_envs)],
        slot_ids=list(range(n_envs)), epsilons=actor_epsilons(n_envs),
        chunk_transitions=16)


def _drive(fam, params, n_steps, seed=1):
    fam.reset_all()
    key = jax.random.key(seed)
    stats, msgs = [], []
    for _ in range(n_steps):
        key, k = jax.random.split(key)
        stats.extend(fam.step_all(params, k))
        msgs.extend(fam.poll_msgs())
    msgs.extend(m for b in fam.builders
                for m in ({"payload": c, "priorities": c.pop("priorities"),
                           "n_trans": int(c["n_trans"])}
                          for c in b.force_flush()))
    fam.close()
    return stats, msgs


def _tree_equal(a, b):
    la = jax.tree.leaves(a)
    lb = jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# -- shard routing -----------------------------------------------------------

def test_infer_shard_routing_pins():
    """The identity hash is a PINNED function: routing recomputes
    identically anywhere (actor, controller, test, ops shell)."""
    assert [infer_shard(f"actor-{i}", 2) for i in range(4)] == [1, 0, 1, 0]
    assert [infer_shard(f"actor-{i}", 3) for i in range(4)] == [1, 1, 2, 0]
    # degenerate/fleet-wide invariants
    assert infer_shard("actor-0", 1) == 0
    assert all(0 <= infer_shard(f"actor-{i}", 5) < 5 for i in range(64))
    # the shard count is IN the key: a re-shard remaps uniformly instead
    # of fixing the low shards' population
    assert {infer_shard(f"actor-{i}", 4) for i in range(64)} == {0, 1, 2, 3}


def test_make_infer_client_targets_home_shard():
    cfg = _cfg(n_shards=2)
    client = make_infer_client(cfg.comms, "actor-0", wait_s=0.1,
                               reprobe_s=0.0)
    try:
        assert client.shard == infer_shard("actor-0", 2) == 1
        g = client.gauges()
        assert g["infer_shard"] == 1
        assert "infer_epoch_seen" in g and "infer_stale_epoch" in g
    finally:
        client.close()


# -- the server-side version gate --------------------------------------------

def test_gate_pin_canary_rollback_promote():
    """The whole gate lifecycle, host-side: canary stashes the incumbent
    once, newer installs track the stream, rollback restores the stash
    bit-identically and pins, pinned installs are held (counted), and
    promote clears everything."""
    cfg = _cfg(n_shards=1)
    model_spec, *_ = dqn_env_specs(cfg)
    model, p1 = _params(cfg, model_spec, seed=0)
    _, p2 = _params(cfg, model_spec, seed=7)
    server = InferServer(cfg.comms, make_policy_fn(model), heartbeat=False)
    try:
        server.set_params(5, p1, epoch=1)
        st = server.apply_ctl({"cmd": "canary", "rid": 1})
        assert st["has_incumbent"] and not st["pinned"] and st["rid"] == 1
        # canary is idempotent: a second one must NOT re-stash (it would
        # replace the incumbent with the candidate)
        server.set_params(9, p2, epoch=1)
        server.apply_ctl({"cmd": "canary"})
        assert server.param_version == 9

        st = server.apply_ctl({"cmd": "rollback", "epoch": 1, "version": 5})
        assert st["pinned"] and st["version"] == 5 and st["epoch"] == 1
        assert server.gate_rollbacks == 1
        _tree_equal(server.params, p1)      # bit-identical restore

        server.set_params(12, p2, epoch=1)  # beyond the pin: held
        assert server.held == 1 and server.param_version == 5
        # at-or-before the pin still installs (a replayed old publish)
        server.set_params(4, p1, epoch=1)
        assert server.param_version == 4

        server.apply_ctl({"cmd": "promote"})
        server.set_params(12, p2, epoch=1)
        assert server.param_version == 12 and server.held == 1
    finally:
        server.close()


def test_gate_orders_epoch_major():
    """The fence is (epoch, version) lexicographic: a pinned shard holds
    a HIGHER epoch even at a lower version, and admits a lower epoch at
    any version — PR 8's life fencing as the major key."""
    cfg = _cfg(n_shards=1)
    model_spec, *_ = dqn_env_specs(cfg)
    model, p1 = _params(cfg, model_spec)
    server = InferServer(cfg.comms, make_policy_fn(model), heartbeat=False)
    try:
        server.set_params(50, p1, epoch=1)
        server.apply_ctl({"cmd": "pin", "epoch": 1, "version": 50})
        server.set_params(2, p1, epoch=2)       # new life, tiny version
        assert server.held == 1 and server.learner_epoch == 1
        server.set_params(49, p1, epoch=1)      # same life, older: fine
        assert server.param_version == 49
        assert fence.beyond(2, 2, (1, 50))      # the helper agrees
    finally:
        server.close()


def test_rollback_without_incumbent_serves_dry():
    """A respawned canary shard that picked the candidate off the stream
    with no stash must NOT keep serving the rejected model: rollback
    drops it to dry replies (clients act locally, bit-identically) until
    promotion unpins."""
    cfg = _cfg(n_shards=1)
    model_spec, *_ = dqn_env_specs(cfg)
    model, p2 = _params(cfg, model_spec, seed=7)
    server = InferServer(cfg.comms, make_policy_fn(model), heartbeat=False)
    try:
        server.set_params(9, p2, epoch=1)       # candidate, no stash
        server.apply_ctl({"cmd": "rollback", "epoch": 1, "version": 5})
        assert server.params is None            # dry until promotion
        assert server.ctl_state()["pinned"]
    finally:
        server.close()


def test_gate_freeze_and_idempotent_rollback():
    """The steady-state verb: freeze stashes + pins at the shard's OWN
    fence, so a non-canary shard that had drifted with the stream still
    has a judged model to restore — and the per-tick rollback reconcile
    is a no-op on an already-rolled-back shard (it must never push a
    healthy frozen shard to dry replies)."""
    cfg = _cfg(n_shards=1)
    model_spec, *_ = dqn_env_specs(cfg)
    model, p2 = _params(cfg, model_spec, seed=7)
    server = InferServer(cfg.comms, make_policy_fn(model), heartbeat=False)
    try:
        server.set_params(9, p2, epoch=1)
        st = server.apply_ctl({"cmd": "freeze"})
        assert st["pinned"] and st["pin"] == [1, 9] and st["has_incumbent"]
        server.set_params(12, p2, epoch=1)      # frozen: held
        assert server.held == 1 and server.param_version == 9
        # rollback against an OLDER controller fence restores the
        # shard's own stash (a no-op here) and pins at the stash fence —
        # never dry, never the controller's stale number
        for _ in range(3):                      # reconcile is idempotent
            st = server.apply_ctl({"cmd": "rollback", "epoch": 1,
                                   "version": 5})
        assert st["has_params"] and st["version"] == 9
        assert st["pin"] == [1, 9]
        assert server.gate_rollbacks == 0       # nothing actually moved
    finally:
        server.close()


def test_ctl_round_trip_over_socket():
    """The ctl channel multiplexes on the serving ROUTER: a DEALER
    command gets a ("ctl_ok", state) reply with the rid echoed."""
    import zmq

    cfg = _cfg(n_shards=1)
    model_spec, *_ = dqn_env_specs(cfg)
    model, p1 = _params(cfg, model_spec)
    server, stop, t = _serve(cfg, model, p1, shard=0, version=5, epoch=1)
    sock = zmq.Context.instance().socket(zmq.DEALER)
    sock.setsockopt(zmq.IDENTITY, b"serve-ctl-0")
    sock.connect(f"tcp://127.0.0.1:{shard_port(cfg.comms, 0)}")
    try:
        sock.send(wire.dumps(("ctl", {"cmd": "pin", "epoch": 1,
                                      "version": 5, "rid": 42})))
        assert sock.poll(10_000, zmq.POLLIN), "no ctl reply"
        kind, body = wire.restricted_loads(sock.recv())
        assert kind == "ctl_ok"
        assert body["rid"] == 42 and body["pinned"] and body["shard"] == 0
        assert body["pin"] == [1, 5]
    finally:
        sock.close(linger=0)
        stop.set()
        t.join(timeout=10)
        server.close()


# -- per-shard bit-parity ----------------------------------------------------

def test_sharded_replies_bit_identical_to_local():
    """Two shards, two workers hashed to DIFFERENT shards (the pinned
    mapping: actor-0 -> 1, actor-1 -> 0 at n=2): each worker's remote
    trajectories equal its pure-local twin bit for bit, every step
    actually remote, and both shards served traffic."""
    cfg = _cfg(n_shards=2)
    model_spec, *_ = dqn_env_specs(cfg)
    model, params = _params(cfg, model_spec)
    s0, stop0, t0 = _serve(cfg, model, params, shard=0)
    s1, stop1, t1 = _serve(cfg, model, params, shard=1)
    clients = []
    try:
        for ident in ("actor-0", "actor-1"):
            local = _family(cfg, model_spec, 2)
            stats_l, msgs_l = _drive(local, params, 60)

            remote = _family(cfg, model_spec, 2)
            remote.attach_infer(make_infer_client(cfg.comms, ident,
                                                  wait_s=30.0))
            clients.append(remote.infer)
            stats_r, msgs_r = _drive(remote, params, 60)

            assert remote.infer.remote_steps > 0
            assert remote.infer.fallbacks == 0
            assert [(s.actor_id, s.reward, s.length) for s in stats_l] \
                == [(s.actor_id, s.reward, s.length) for s in stats_r]
            assert len(msgs_l) == len(msgs_r)
            for ma, mb in zip(msgs_l, msgs_r):
                np.testing.assert_array_equal(ma["priorities"],
                                              mb["priorities"])
    finally:
        stop0.set()
        stop1.set()
        t0.join(timeout=10)
        t1.join(timeout=10)
        s0.close()
        s1.close()
    assert {c.shard for c in clients} == {0, 1}
    assert s0.requests > 0 and s1.requests > 0, \
        "both shards must have served their hashed band"


def test_dead_shard_degrades_to_local_and_reprobes_back():
    """A dead home shard costs its worker band the single-server
    semantics exactly: local fallback after the wait, down-marker, and a
    re-probe that regains the (re)spawned shard with no worker restart —
    while the OTHER shard's existence changes nothing for this band."""
    cfg = _cfg(n_shards=2)
    model_spec, *_ = dqn_env_specs(cfg)
    model, params = _params(cfg, model_spec)
    # only shard 0 is up; actor-0's home shard (1) is dark
    s0, stop0, t0 = _serve(cfg, model, params, shard=0)
    fam = _family(cfg, model_spec, 2)
    fam.attach_infer(make_infer_client(cfg.comms, "actor-0", wait_s=0.3,
                                       reprobe_s=0.3))
    client = fam.infer
    fam.reset_all()
    key = jax.random.key(1)
    s1 = stop1 = t1 = None
    try:
        for _ in range(3):
            key, k = jax.random.split(key)
            fam.step_all(params, k)
        assert client.fallbacks > 0 and client.remote_steps == 0

        s1, stop1, t1 = _serve(cfg, model, params, shard=1)
        deadline = time.monotonic() + 30.0
        while client.remote_steps == 0 and time.monotonic() < deadline:
            key, k = jax.random.split(key)
            fam.step_all(params, k)
            time.sleep(0.05)
        assert client.remote_steps > 0, "re-probe never regained shard 1"
        assert client.reprobes > 0
        assert s0.requests == 0, "wrong shard took actor-0's traffic"
    finally:
        fam.close()
        stop0.set()
        t0.join(timeout=10)
        s0.close()
        if s1 is not None:
            stop1.set()
            t1.join(timeout=10)
            s1.close()


# -- the canary state machine ------------------------------------------------

def _ctrl(n_shards=2, soak_s=10.0, version_every=50, **kw):
    t = {"now": 0.0}
    c = DeployController(n_shards, canary_frac=0.5, soak_s=soak_s,
                         version_every=version_every,
                         clock=lambda: t["now"],
                         wall=lambda: 1_000_000.0 + t["now"], **kw)
    return c, t


def test_canary_to_promoted_on_healthy_soak():
    c, t = _ctrl()
    cmds = c.tick({"epoch": 1, "version": 10}, SLO_OK)
    assert c.incumbent == (1, 10) and c.state == IDLE
    assert all(cmd["cmd"] == "promote" for _, cmd in cmds)

    t["now"] = 5.0          # spacing not met: no deployment
    c.tick({"epoch": 1, "version": 30}, SLO_OK)
    assert c.state == IDLE and c.deployments == 0

    t["now"] = 10.0         # version 60 >= 10 + 50: canary
    cmds = dict(c.tick({"epoch": 1, "version": 60}, SLO_OK))
    assert c.state == CANARY and c.deployments == 1
    assert c.canary_shards == (0,)
    assert cmds[0]["cmd"] == "canary"
    # the non-canary shard FREEZES at its own judged fence (the
    # latest-wins stream would otherwise have drifted it past the
    # incumbent, leaving a rollback nothing judged to restore)
    assert cmds[1] == {"cmd": "freeze"}

    t["now"] = 22.0         # 12s of healthy soak (>= 10): promote,
    cmds = dict(c.tick({"epoch": 1, "version": 70}, SLO_OK))
    assert c.state == PROMOTED and c.promotions == 1
    assert c.incumbent == (1, 70)   # the canary tracked the live stream
    # the gate opens so every shard takes the judged version...
    assert all(cmd["cmd"] == "promote" for cmd in cmds.values())
    # ...then the tier re-freezes once gate_open_s (default 10) passes
    t["now"] = 40.0
    cmds = dict(c.tick({"epoch": 1, "version": 75}, SLO_OK))
    assert c.state == PROMOTED
    assert all(cmd["cmd"] == "freeze" for cmd in cmds.values())


def test_canary_to_rolled_back_on_breach_and_no_recanary():
    c, t = _ctrl()
    c.tick({"epoch": 1, "version": 10}, SLO_OK)
    t["now"] = 5.0
    c.tick({"epoch": 1, "version": 60}, SLO_OK)
    assert c.state == CANARY

    t["now"] = 7.0
    cmds = dict(c.tick({"epoch": 1, "version": 65},
                       {"eval_score": "BREACHED",
                        "infer_rt_p99_ms": "OK"}))
    assert c.state == ROLLED_BACK and c.rollbacks == 1
    assert c.incumbent == (1, 10)       # incumbent NEVER moved
    assert c.rejected == (1, 65)
    # the rollback edge reaches every shard, by epoch AND version
    assert all(cmd == {"cmd": "rollback", "epoch": 1, "version": 10}
               for cmd in cmds.values())

    # the rejected fence is never re-canaried; spacing restarts from it
    t["now"] = 12.0
    c.tick({"epoch": 1, "version": 80}, SLO_OK)
    assert c.state == ROLLED_BACK and c.deployments == 1
    t["now"] = 17.0
    c.tick({"epoch": 1, "version": 120}, SLO_OK)
    assert c.state == CANARY and c.deployments == 2


def test_epoch_bump_always_deploys_and_unknown_slo_holds():
    c, t = _ctrl(version_every=1000)     # spacing alone would never fire
    c.tick({"epoch": 1, "version": 10}, SLO_OK)
    t["now"] = 5.0
    c.tick({"epoch": 2, "version": 2}, SLO_OK)   # restarted learner
    assert c.state == CANARY, "a new learner epoch IS a new model"
    # unreadable SLO: soak credit resets — no promotion however long
    t["now"] = 50.0
    c.tick({"epoch": 2, "version": 3}, None)
    t["now"] = 55.0
    c.tick({"epoch": 2, "version": 3}, SLO_OK)   # credit restarts here
    t["now"] = 60.0
    c.tick({"epoch": 2, "version": 3}, SLO_OK)
    assert c.state == CANARY, "held ticks must not count toward soak"
    t["now"] = 66.0
    c.tick({"epoch": 2, "version": 3}, SLO_OK)
    assert c.state == PROMOTED and c.incumbent == (2, 3)


def test_deployment_timeline_schema_pin():
    """The timeline is the drill's evidence format — its schema is a
    contract (CI serve-smoke greps these exact keys/edges)."""
    c, t = _ctrl()
    c.tick({"epoch": 1, "version": 10}, SLO_OK)
    t["now"] = 5.0
    c.tick({"epoch": 1, "version": 60}, SLO_OK)
    t["now"] = 20.0
    c.tick({"epoch": 1, "version": 60}, SLO_OK)
    snap = c.snapshot()
    assert snap["kind"] == "apex_serving" and snap["version"] == 1
    assert set(snap) >= {"state", "n_shards", "canary_shards",
                         "incumbent", "candidate", "rejected",
                         "deployments", "promotions", "rollbacks",
                         "shards", "timeline"}
    assert snap["incumbent"] == {"epoch": 1, "version": 60, "id": "1:60"}
    edges = [(e["from"], e["to"]) for e in snap["timeline"]]
    assert (IDLE, CANARY) in edges and (CANARY, PROMOTED) in edges
    for e in snap["timeline"]:
        assert set(e) == {"t_s", "wall", "version", "from", "to",
                          "reason"}


def test_single_shard_tier_canaries_whole_tier():
    c, _ = _ctrl(n_shards=1)
    assert c.canary_shards == (0,)
    c2, _ = _ctrl(n_shards=4)
    # frac 0.5 of 4 = 2 canary shards, 2 pinned incumbents
    assert c2.canary_shards == (0, 1)


# -- evidence surfaces -------------------------------------------------------

def test_serving_stat_survives_the_restricted_wire():
    c, t = _ctrl()
    c.tick({"epoch": 1, "version": 10}, SLO_OK)
    stat = ServingStat("serve-ctl", c.snapshot())
    got = wire.restricted_loads(wire.dumps(stat))
    assert got.identity == "serve-ctl"
    assert got.snapshot["incumbent"]["id"] == "1:10"


def test_serving_section_on_status_table_and_prometheus():
    from apex_tpu.fleet.registry import format_fleet_table
    from apex_tpu.obs import metrics as obs_metrics

    c, t = _ctrl()
    c.tick({"epoch": 1, "version": 10}, SLO_OK)
    t["now"] = 5.0
    c.tick({"epoch": 1, "version": 60}, SLO_OK)
    c.shard_view[0] = {"shard": 0, "pinned": False, "epoch": 1,
                       "version": 60, "held": 0, "rollbacks": 0}
    c.shard_view[1] = {"shard": 1, "pinned": True, "epoch": 1,
                       "version": 10, "held": 3, "rollbacks": 0}
    serving = c.snapshot()

    table = format_fleet_table({"peers": [], "metrics": {},
                                "serving": serving})
    assert "serving: CANARY" in table
    assert "serving shard 1: PINNED model=1:10 held=3" in table

    gauges, labeled = prometheus_sections(serving)
    # every family is registered (J015's contract — an unregistered row
    # would be unscrapeable)
    for name in list(gauges) + list(labeled):
        assert name in obs_metrics.REGISTERED_FAMILIES, name
    text = obs_metrics.render(gauges=gauges, labeled=labeled)
    assert 'apex_serving_state{state="CANARY"} 1.0' in text
    assert 'apex_serving_shard_pinned{shard="1"} 1.0' in text


def test_serve_gauges_are_registered():
    """Every literal key the shard servers and the controller put into
    heartbeat gauges is in the declared registry (J015 backs this up
    statically; the runtime pin keeps the two from drifting)."""
    from apex_tpu.obs import metrics as obs_metrics

    cfg = _cfg(n_shards=1)
    model_spec, *_ = dqn_env_specs(cfg)
    model, p1 = _params(cfg, model_spec)
    server = InferServer(cfg.comms, make_policy_fn(model), heartbeat=False)
    try:
        for key in server.gauges():
            assert key in obs_metrics.REGISTERED_GAUGES, key
    finally:
        server.close()
    client = make_infer_client(cfg.comms, "actor-0")
    try:
        for key in client.gauges():
            assert key in obs_metrics.REGISTERED_GAUGES, key
    finally:
        client.close()


# -- CLI ---------------------------------------------------------------------

def test_cli_serving_flags_and_env_twins(monkeypatch):
    from apex_tpu.runtime.cli import build_parser, config_from_args

    monkeypatch.setenv("APEX_INFER_SHARDS", "3")
    monkeypatch.setenv("INFER_SHARD_ID", "2")
    monkeypatch.setenv("APEX_SERVE_CANARY_FRAC", "0.25")
    monkeypatch.setenv("APEX_SERVE_SOAK_S", "12.5")
    monkeypatch.setenv("APEX_SERVE_VERSION_EVERY", "40")
    monkeypatch.setenv("APEX_SERVE_INTERVAL_S", "1.5")
    args = build_parser().parse_args([])
    cfg = config_from_args(args)
    assert cfg.comms.infer_shards == 3
    assert args.infer_shard_id == 2
    assert args.serve_canary_frac == 0.25
    assert args.serve_soak == 12.5
    assert args.serve_version_every == 40
    assert args.serve_interval == 1.5
    # the serve-ctl role parses
    args2 = build_parser().parse_args(["--role", "serve-ctl"])
    assert args2.role == "serve-ctl"


def test_infer_role_rejects_out_of_range_shard():
    from apex_tpu.infer_service.service import run_infer_server

    cfg = _cfg(n_shards=2)
    with pytest.raises(ValueError, match="outside"):
        run_infer_server(cfg, server_id=5)
