"""Obs plane (apex_tpu/obs): chunk lineage spans, the trace ring + merge
tool, the learner-side latency join, Prometheus rendering, and the
DispatchGapTimer percentile fix.

Everything is tier-1: fake clocks / scripted pools, no sockets except
where the surface IS a socket (the /metrics scrape round-trip lives in
``tests/test_fleet.py`` beside the status-server tests)."""

from __future__ import annotations

import copy
import json
import os

import numpy as np
import pytest

from apex_tpu.obs import merge as obs_merge
from apex_tpu.obs import metrics as obs_metrics
from apex_tpu.obs import spans as obs_spans
from apex_tpu.obs.spans import LatencyHistogram, LearnerObs
from apex_tpu.obs.trace import TraceRing
from apex_tpu.utils.metrics import percentile

# the same real-builder chunk stream the ingest-pipeline suite uses
from tests.test_ingest_pipeline import (_assert_states_identical,
                                        _pool_spec,
                                        _random_chunk_messages)


# -- percentiles (satellite: DispatchGapTimer even-median fix) ---------------

def test_percentile_nearest_rank():
    assert percentile([], 0.5) == 0.0
    assert percentile([5.0], 0.5) == 5.0
    assert percentile([1, 2, 3], 0.5) == 2
    # EVEN length: the lower middle element, not the upper (the old
    # ``vals[n // 2]`` picked 3 here)
    assert percentile([1, 2, 3, 4], 0.5) == 2
    assert percentile([1, 2, 3, 4], 0.9) == 4
    assert percentile(list(range(1, 101)), 0.99) == 99
    assert percentile(list(range(1, 101)), 0.90) == 90


def test_dispatch_gap_snapshot_percentiles():
    from apex_tpu.utils.profiling import DispatchGapTimer

    t = DispatchGapTimer()
    # inject a known gap distribution (the clock-driven path is exercised
    # by every trainer test; here the math is the contract)
    gaps = [0.001 * g for g in (1, 2, 3, 4, 5, 6, 7, 8, 9, 10)]
    t._gaps.extend(gaps)
    t.count = len(gaps)
    t.total = sum(gaps)
    t.max = max(gaps)
    snap = t.snapshot()
    assert snap["dispatch_gap_ms_p50"] == pytest.approx(5.0)   # lower mid
    assert snap["dispatch_gap_ms_p90"] == pytest.approx(9.0)
    assert snap["dispatch_gap_ms_p99"] == pytest.approx(10.0)
    assert snap["dispatch_gap_ms_max"] == pytest.approx(10.0)
    assert snap["dispatches"] == 10


# -- span lifecycle ----------------------------------------------------------

def test_drain_builder_chunks_stamps_sealed_and_send_marks_version():
    msgs = _random_chunk_messages(seed=3, n_chunks=2)
    for msg in msgs:
        spans = obs_spans.spans_of(msg)
        assert len(spans) == 1
        assert "sealed" in spans[0]["hops"]
        obs_spans.mark_send(msg, param_version=42)
        assert spans[0]["pv"] == 42
        assert "send" in spans[0]["hops"]
        # stamps are first-wins: a second recv keeps the earlier time
        obs_spans.stamp(msg, "recv")
        first = spans[0]["hops"]["recv"]
        obs_spans.stamp(msg, "recv")
        assert spans[0]["hops"]["recv"] is first
    # payload NEVER carries timestamps (the merge bit-parity contract)
    assert obs_spans.SPAN_KEY not in msgs[0]["payload"]


def test_span_stamping_disabled_by_env(monkeypatch):
    monkeypatch.setenv("APEX_OBS_SPANS", "0")
    msgs = _random_chunk_messages(seed=4, n_chunks=1)
    assert obs_spans.spans_of(msgs[0]) == []
    obs_spans.mark_send(msgs[0], 5)          # no-op while disabled
    assert obs_spans.SPAN_KEY not in msgs[0]


def test_mark_send_creates_span_on_bare_message():
    msg = {"payload": {}, "priorities": None, "n_trans": 1}
    obs_spans.mark_send(msg, 7)
    spans = obs_spans.spans_of(msg)
    assert spans[0]["pv"] == 7
    assert {"sealed", "send"} <= spans[0]["hops"].keys()


# -- span round-trip through the merges (payload bit-parity re-pinned) -------

@pytest.mark.parametrize("m", [2, 4])
def test_merge_chunk_messages_carries_spans_and_keeps_payload_parity(m):
    """Span-stamped messages merge to one message carrying m spans (merge
    hop stamped), and the merged PAYLOAD stays bit-identical to merging
    the same stream without spans — timestamps ride metadata only."""
    msgs = _random_chunk_messages(seed=10 + m, n_chunks=m)
    for i, msg in enumerate(msgs):
        obs_spans.mark_send(msg, param_version=i)
    bare = copy.deepcopy(msgs)
    for msg in bare:
        msg.pop(obs_spans.SPAN_KEY, None)

    from apex_tpu.training.ingest_pipeline import merge_chunk_messages
    merged = merge_chunk_messages(copy.deepcopy(msgs))
    merged_bare = merge_chunk_messages(bare)

    spans = obs_spans.spans_of(merged)
    assert len(spans) == m
    assert [s["pv"] for s in spans] == list(range(m))
    assert all("merge" in s["hops"] for s in spans)
    assert obs_spans.SPAN_KEY not in merged["payload"]
    for key in merged_bare["payload"]:
        if key == "extras":
            continue
        assert np.array_equal(np.asarray(merged["payload"][key]),
                              np.asarray(merged_bare["payload"][key])), key
    assert np.array_equal(np.asarray(merged["priorities"]),
                          np.asarray(merged_bare["priorities"]))

    # and the replay-state parity contract itself still holds with spans on
    pool = _pool_spec()
    seq = pool.init()
    for msg in msgs:
        seq = pool.add(seq, msg["payload"],
                       np.asarray(msg["priorities"], np.float32))
    one = pool.add(pool.init(), merged["payload"],
                   np.asarray(merged["priorities"], np.float32))
    _assert_states_identical(seq, one)


def test_merge_group_messages_carries_spans(monkeypatch):
    from apex_tpu.parallel.aggregate import stack_chunk_messages
    from apex_tpu.training.ingest_pipeline import merge_group_messages

    n_dp, m = 2, 3
    groups = []
    for g in range(m):
        chunk_msgs = _random_chunk_messages(seed=50 + g, n_chunks=n_dp)
        for msg in chunk_msgs:
            obs_spans.mark_send(msg, param_version=g)
        payload, prios, n_tr = stack_chunk_messages(chunk_msgs)
        group = {"payload": payload, "priorities": prios, "n_trans": n_tr,
                 obs_spans.SPAN_KEY: obs_spans.merge_spans(chunk_msgs)}
        groups.append(group)
    merged = merge_group_messages(copy.deepcopy(groups), n_dp)
    spans = obs_spans.spans_of(merged)
    assert len(spans) == n_dp * m            # one per SOURCE chunk
    assert obs_spans.SPAN_KEY not in merged["payload"]
    assert sorted({s["pv"] for s in spans}) == list(range(m))


def test_chunk_aggregator_stamps_merge_and_flattens_spans():
    from apex_tpu.parallel.aggregate import ChunkAggregator
    from tests.test_ingest_pipeline import ScriptedPool

    msgs = _random_chunk_messages(seed=9, n_chunks=4)
    for msg in msgs:
        obs_spans.mark_send(msg, 1)
    agg = ChunkAggregator(ScriptedPool(msgs), n_dp=2)
    groups = agg.poll_chunks(4)
    assert len(groups) == 2
    for group in groups:
        spans = obs_spans.spans_of(group)
        assert len(spans) == 2
        assert all("merge" in s["hops"] for s in spans)


# -- the learner-side join ---------------------------------------------------

def test_latency_histogram_snapshot():
    h = LatencyHistogram(window=100)
    for v in (1, 2, 3, 4):
        h.record(v)
    s = h.snapshot()
    assert s["count"] == 4
    assert s["p50_s"] == 2.0            # even window, lower middle
    assert s["p99_s"] == 4.0
    assert s["max_s"] == 4.0
    assert s["mean_s"] == pytest.approx(2.5)


def test_learner_obs_joins_frame_age_and_param_lag():
    mono, wall = [100.0], [1000.0]
    obs = LearnerObs(clock=lambda: mono[0], wall=lambda: wall[0])
    obs.note_publish(7)                  # pv 7 published at mono=100

    span = {"pv": 7, "hops": {"sealed": (100.5, 1000.5),
                              "send": (100.6, 1000.6)}}
    mono[0], wall[0] = 103.0, 1003.0     # consumed 3s later
    obs.pre_consume([span])
    assert "consume" in span["hops"]
    obs.post_consume([span])
    assert "prio_wb" in span["hops"]
    assert obs.frame_age.count == 1
    # sealed at wall 1000.5, consumed at wall 1003 -> 2.5s frame age
    assert obs.frame_age.snapshot()["p50_s"] == pytest.approx(2.5)
    # published at mono 100, consumed at mono 103 -> 3s propagation lag
    assert obs.param_lag.snapshot()["p50_s"] == pytest.approx(3.0)

    # unknown version / missing sealed: joins skip, nothing crashes
    obs.post_consume([{"pv": 99, "hops": {}}])
    assert obs.param_lag.count == 1
    sc = obs.scalars()
    assert sc["obs_spans_consumed"] == 2
    assert set(obs.summary()) == {"frame_age_at_train_s",
                                  "param_propagation_lag_s",
                                  "spans_consumed"}


def test_learner_obs_publish_ledger_is_bounded():
    obs = LearnerObs(max_versions=4, clock=lambda: 0.0, wall=lambda: 0.0)
    for v in range(10):
        obs.note_publish(v)
    assert len(obs._pub) == 4 and 9 in obs._pub and 0 not in obs._pub


def test_learner_obs_emits_lineage_events():
    ring = TraceRing("learner", enabled=True)
    obs = LearnerObs(ring=ring, clock=lambda: 5.0, wall=lambda: 105.0)
    span = {"pv": 1, "hops": {"sealed": (1.0, 101.0),
                              "send": (2.0, 102.0),
                              "recv": (3.0, 103.0)}}
    obs.pre_consume([span])
    obs.post_consume([span])
    chrome = ring.to_chrome()
    names = [ev["name"] for ev in chrome["traceEvents"]
             if ev.get("ph") == "X"]
    assert "sealed→send" in names and "send→recv" in names
    # lineage events use the wall timebase directly
    ev = next(e for e in chrome["traceEvents"] if e["name"] == "sealed→send")
    assert ev["ts"] == pytest.approx(101.0 * 1e6)
    assert ev["dur"] == pytest.approx(1e6)


# -- pipeline carries spans into staged slots --------------------------------

def test_ingest_pipeline_slots_carry_staged_spans():
    from apex_tpu.training.ingest_pipeline import IngestPipeline
    from tests.test_ingest_pipeline import ScriptedPool

    msgs = _random_chunk_messages(seed=21, n_chunks=4)
    for msg in msgs:
        obs_spans.mark_send(msg, 3)
    pipe = IngestPipeline(ScriptedPool(msgs), depth=4, merge_max=1,
                          put_device=False)
    pipe.start()
    try:
        got = []
        while len(got) < 4:
            slot = pipe.poll_slot(timeout=5.0)
            assert slot is not None
            got.append(slot)
        for slot in got:
            assert len(slot.spans) == 1
            hops = slot.spans[0]["hops"]
            assert {"sealed", "send", "recv", "stage"} <= hops.keys()
            # pipeline ordering: recv happened at/after send, stage after
            assert hops["recv"][0] >= hops["send"][0]
            assert hops["stage"][0] >= hops["recv"][0]
    finally:
        pipe.stop()


# -- end-to-end: trainer join over a scripted stream -------------------------

def test_trainer_latency_summary_end_to_end():
    """A real (tiny) ApexTrainer over a span-stamped scripted stream: the
    latency section fills — frame-age and param-lag histograms count
    consumed spans, obs_* scalars reach the metric log."""
    import dataclasses

    from apex_tpu.config import small_test_config
    from apex_tpu.replay.frame_chunks import FrameChunkBuilder
    from apex_tpu.training.apex import ApexTrainer
    from tests.test_ingest_pipeline import ScriptedPool

    # chunks in the trainer's env geometry (CartPole: 4-dim, stack 1),
    # drained through the real message factory so spans are born there
    from apex_tpu.actors.pool import drain_builder_chunks
    rng = np.random.default_rng(31)
    builder = FrameChunkBuilder(3, 0.99, 1, (4,), chunk_transitions=8,
                                frame_dtype=np.float32)
    msgs: list[dict] = []
    while len(msgs) < 24:
        builder.begin_episode(rng.normal(size=4).astype(np.float32))
        ep_len = int(rng.integers(4, 30))
        for t in range(ep_len):
            builder.add_step(int(rng.integers(0, 2)), float(rng.normal()),
                             rng.normal(size=2).astype(np.float32),
                             rng.normal(size=4).astype(np.float32),
                             terminated=t == ep_len - 1, truncated=False)
        msgs.extend(drain_builder_chunks(builder))
    msgs = msgs[:24]
    for msg in msgs:
        obs_spans.mark_send(msg, param_version=1)
    cfg = small_test_config(capacity=256, batch_size=8, n_actors=1)
    cfg = cfg.replace(replay=dataclasses.replace(cfg.replay, warmup=32),
                      learner=dataclasses.replace(
                          cfg.learner, target_update_interval=50))
    trainer = ApexTrainer(cfg, pool=ScriptedPool(msgs),
                          publish_min_seconds=30.0, respawn_workers=False)
    trainer.train(total_steps=6, max_seconds=60, log_every=2)
    latency = trainer.latency_summary()
    assert latency is not None
    assert latency["spans_consumed"] > 0
    assert latency["frame_age_at_train_s"]["count"] > 0
    assert latency["frame_age_at_train_s"]["p50_s"] >= 0
    # the acted-under version was published by this trainer (version 1 is
    # its first publish), so the propagation-lag join found it
    assert latency["param_propagation_lag_s"]["count"] > 0
    assert "dispatch_gap_ms" in latency
    assert "dispatch_gap_ms_p90" in latency["dispatch_gap_ms"]
    assert any(tag.endswith("obs_frame_age_p50_s")
               for tag in trainer.log.history)


# -- trace ring --------------------------------------------------------------

def test_trace_ring_bounded_sampled_and_wall_converted():
    ring = TraceRing("actor-0", enabled=True, capacity=8, sample=1)
    for i in range(20):
        ring.complete("phase", float(i), 0.5, track="t")
    chrome = ring.to_chrome()
    xs = [ev for ev in chrome["traceEvents"] if ev.get("ph") == "X"]
    assert len(xs) == 8                  # bounded: only the newest 8
    # perf->wall conversion uses the anchor
    anchor = chrome["metadata"]["clock_sync"]
    want = (anchor["wall"] + (19.0 - anchor["perf"])) * 1e6
    assert xs[-1]["ts"] == pytest.approx(want, abs=1.0)
    # process/thread naming metadata present
    assert any(ev.get("name") == "process_name"
               and ev["args"]["name"] == "actor-0"
               for ev in chrome["traceEvents"])
    assert any(ev.get("name") == "thread_name"
               and ev["args"]["name"] == "t"
               for ev in chrome["traceEvents"])

    sampled = TraceRing("x", enabled=True, capacity=100, sample=4)
    for i in range(20):
        sampled.complete("e", float(i), 0.1)
    assert sum(1 for ev in sampled.to_chrome()["traceEvents"]
               if ev.get("ph") == "X") == 5

    off = TraceRing("y", enabled=False)
    off.complete("e", 0.0, 0.1)
    assert sum(1 for ev in off.to_chrome()["traceEvents"]
               if ev.get("ph") == "X") == 0


def test_get_ring_disabled_without_env(monkeypatch, tmp_path):
    from apex_tpu.obs import trace as obs_trace

    monkeypatch.delenv("APEX_TRACE_DIR", raising=False)
    obs_trace.reset_for_tests()
    try:
        ring = obs_trace.get_ring()
        assert not ring.enabled
        assert obs_trace.dump_ring() is None
    finally:
        obs_trace.reset_for_tests()


def test_ring_dump_and_phase_timer_integration(monkeypatch, tmp_path):
    from apex_tpu.obs import trace as obs_trace
    from apex_tpu.utils.profiling import PhaseTimer

    monkeypatch.setenv("APEX_TRACE_DIR", str(tmp_path))
    monkeypatch.setenv("APEX_TRACE_FLUSH_S", "0")   # no flusher thread
    obs_trace.reset_for_tests()
    try:
        obs_trace.set_process_label("actor-7")
        ring = obs_trace.get_ring()
        assert ring.enabled
        timer = PhaseTimer(ring=ring, track="phases")
        with timer.phase("env_step"):
            pass
        path = obs_trace.dump_ring()
        assert path is not None and os.path.exists(path)
        with open(path) as fh:
            data = json.load(fh)
        assert data["metadata"]["label"] == "actor-7"
        assert any(ev.get("name") == "env_step"
                   for ev in data["traceEvents"])
    finally:
        obs_trace.reset_for_tests()


# -- merge: clock alignment --------------------------------------------------

def _fake_trace(label: str, events: list[tuple[str, float, float]]) -> dict:
    return {
        "traceEvents": [
            {"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
             "args": {"name": label}},
        ] + [
            {"name": name, "ph": "X", "pid": 1, "tid": 1,
             "ts": ts_s * 1e6, "dur": dur_s * 1e6}
            for name, ts_s, dur_s in events
        ],
        "metadata": {"label": label},
    }


def test_merge_traces_aligns_skewed_clocks_into_one_ordered_timeline():
    """Two processes with skewed wall clocks: actor-0's clock runs 5s
    AHEAD of the learner's.  True order is learner.a (t=10), actor.b
    (true t=11, stamped 16), learner.c (t=12).  Without offsets the
    merged order is wrong; with the heartbeat-derived offset (-5s for
    actor-0) the timeline is correct and ordered."""
    learner = _fake_trace("learner", [("a", 10.0, 0.1), ("c", 12.0, 0.1)])
    actor = _fake_trace("actor-0", [("b", 16.0, 0.1)])

    naive = obs_merge.merge_traces([learner, actor])
    naive_names = [ev["name"] for ev in naive["traceEvents"]
                   if ev.get("ph") == "X"]
    assert naive_names == ["a", "c", "b"]            # skew-corrupted order

    merged = obs_merge.merge_traces([learner, actor],
                                    offsets={"actor-0": -5.0})
    names = [ev["name"] for ev in merged["traceEvents"]
             if ev.get("ph") == "X"]
    assert names == ["a", "b", "c"]                  # true order restored
    # timeline re-zeroed at the earliest event and pids remapped per file
    ts = {ev["name"]: ev["ts"] for ev in merged["traceEvents"]
          if ev.get("ph") == "X"}
    assert ts["a"] == 0.0
    assert ts["b"] == pytest.approx(1e6)
    assert {ev["pid"] for ev in merged["traceEvents"]} == {1, 2}
    assert merged["metadata"]["offsets_applied"] == {"actor-0": -5.0}


def test_merge_dir_uses_fleet_summary_offsets(tmp_path):
    for label, events in (("learner", [("a", 10.0, 0.1)]),
                          ("actor-0", [("b", 16.0, 0.1)])):
        with open(tmp_path / f"trace-{label}-1.json", "w") as fh:
            json.dump(_fake_trace(label, events), fh)
    with open(tmp_path / "fleet_summary.json", "w") as fh:
        json.dump({"peers": [{"identity": "actor-0",
                              "clock_offset_s": -5.0,
                              "clock_offset_n": 9}]}, fh)
    out = tmp_path / "merged.json"
    merged = obs_merge.merge_dir(str(tmp_path), str(out))
    assert out.exists()
    names = [ev["name"] for ev in merged["traceEvents"]
             if ev.get("ph") == "X"]
    assert names == ["a", "b"]
    assert merged["traceEvents"][-1]["ts"] == pytest.approx(1e6)
    # estimate quality rides the merged metadata for triage
    assert merged["metadata"]["offset_samples"] == {"actor-0": 9}


def test_merge_cli_main(tmp_path, capsys):
    with open(tmp_path / "trace-learner-1.json", "w") as fh:
        json.dump(_fake_trace("learner", [("a", 1.0, 0.1)]), fh)
    rc = obs_merge.main([str(tmp_path)])
    assert rc == 0
    assert (tmp_path / "merged_trace.json").exists()
    assert "perfetto" in capsys.readouterr().out
    assert obs_merge.main([str(tmp_path / "empty")]) == 1


# -- registry clock offsets (the heartbeat join merge consumes) --------------

def test_registry_records_clock_offset_from_heartbeat_wall():
    from apex_tpu.config import CommsConfig
    from apex_tpu.fleet.heartbeat import Heartbeat
    from apex_tpu.fleet.registry import FleetRegistry

    wall = [2000.0]
    reg = FleetRegistry(CommsConfig(), clock=lambda: 1.0,
                        wall_clock=lambda: wall[0])
    reg.observe(Heartbeat("actor-0", wall_ts=1995.0))
    snap = reg.snapshot()
    assert snap["peers"][0]["clock_offset_s"] == pytest.approx(5.0)
    # unstamped beats (wall_ts=0) leave the offset unknown, not garbage
    reg.observe(Heartbeat("actor-1"))
    snap = reg.snapshot()
    peer1 = next(p for p in snap["peers"] if p["identity"] == "actor-1")
    assert peer1["clock_offset_s"] is None


def test_registry_offset_is_min_transit_median_not_last_beat():
    """Each beat samples skew + transit; the published offset must be
    the median of the SMALLEST half of the window (transit is strictly
    additive, so small samples bound the skew), not whatever the last
    beat happened to carry — one queue-dwell spike must not own the
    estimate."""
    from apex_tpu.config import CommsConfig
    from apex_tpu.fleet.heartbeat import Heartbeat
    from apex_tpu.fleet.registry import FleetRegistry, _min_transit_offset

    wall = [0.0]
    reg = FleetRegistry(CommsConfig(), clock=lambda: 1.0,
                        wall_clock=lambda: wall[0])
    # true skew 5.0; transits 0.0, 0.8, 0.1, 4.0 (spike), 0.2
    for t, transit in ((100.0, 0.0), (102.0, 0.8), (104.0, 0.1),
                       (106.0, 4.0), (108.0, 0.2)):
        wall[0] = t + 5.0 + transit
        reg.observe(Heartbeat("actor-0", wall_ts=t))
    snap = reg.snapshot()
    p = snap["peers"][0]
    # smallest half of [5.0, 5.1, 5.2, 5.8, 9.0] -> [5.0, 5.1] -> 5.05
    assert p["clock_offset_s"] == pytest.approx(5.05)
    assert p["clock_offset_n"] == 5
    # the helper's selection semantics, pinned directly
    assert _min_transit_offset([7.0]) == 7.0
    assert _min_transit_offset([5.0, 9.0]) == 5.0
    assert _min_transit_offset([5.0, 5.2, 9.0, 5.1]) == \
        pytest.approx(5.05)
    # window bound: old samples age out (deque maxlen)
    for i in range(40):
        wall[0] = 200.0 + i + 2.0          # skew settles to 2.0
        reg.observe(Heartbeat("actor-0", wall_ts=200.0 + i))
    p = reg.snapshot()["peers"][0]
    assert p["clock_offset_s"] == pytest.approx(2.0)
    assert p["clock_offset_n"] == 16


# -- R2D2 sequence messages: span-stamped at the source drain ----------------

def test_r2d2_drain_grouped_stamps_sealed_spans(monkeypatch):
    """The recurrent family's messages are born with a lineage span in
    message METADATA (like drain_builder_chunks), so the merged timeline
    covers R2D2 too — and the payload stays span-free (the learner's
    fixed sequence-batch shapes depend on it)."""
    from apex_tpu.actors.r2d2 import drain_grouped
    from apex_tpu.obs import spans as obs_spans

    def fake_seqs(n):
        return [{"priority": np.float32(1.0), "n_new": 3,
                 "obs": np.zeros((4, 2), np.float32),
                 "action": np.zeros(4, np.int32)} for _ in range(n)]

    ready = fake_seqs(5)
    msgs = drain_grouped(ready, group=2)
    assert len(msgs) == 2 and len(ready) == 1     # partial group buffered
    for msg in msgs:
        spans = obs_spans.spans_of(msg)
        assert len(spans) == 1
        assert "sealed" in spans[0]["hops"]
        assert obs_spans.SPAN_KEY not in msg["payload"]
    # the kill switch turns stamping off at the source
    monkeypatch.setenv("APEX_OBS_SPANS", "0")
    msgs = drain_grouped(fake_seqs(2), group=2)
    assert obs_spans.SPAN_KEY not in msgs[0]


# -- prometheus rendering ----------------------------------------------------

def test_prometheus_render_sections():
    h = LatencyHistogram()
    for v in (0.1, 0.2, 0.3, 0.4):
        h.record(v)
    text = obs_metrics.render(
        gauges={"learner/loss": 0.25, "skipped": None},
        # apexlint: disable=J015 -- synthetic family name exercising the renderer
        counters={"steps_total": 123},
        histograms={"frame_age_at_train_seconds": h.snapshot()},
        labeled={"fleet_peer_fps": [({"identity": "actor-0"}, 55.0)]})
    assert "# TYPE apex_learner_loss gauge" in text
    assert "apex_learner_loss 0.25" in text
    assert "# TYPE apex_steps_total counter" in text
    assert "apex_steps_total 123.0" in text
    assert ('apex_frame_age_at_train_seconds{quantile="0.5"} 0.2'
            in text)
    assert "apex_frame_age_at_train_seconds_count 4" in text
    assert 'apex_fleet_peer_fps{identity="actor-0"} 55.0' in text
    assert "skipped" not in text
    assert text.endswith("\n")


def test_prometheus_render_fleet_and_tails():
    from collections import deque

    from apex_tpu.config import CommsConfig
    from apex_tpu.fleet.heartbeat import Heartbeat
    from apex_tpu.fleet.registry import FleetRegistry

    reg = FleetRegistry(CommsConfig())
    reg.observe(Heartbeat("actor-0", role="actor", fps=60.0,
                          chunks_sent=9))
    gauges, labeled = obs_metrics.render_fleet(reg.snapshot())
    assert gauges["fleet_alive"] == 1
    assert labeled["fleet_peer_fps"][0][1] == 60.0
    text = obs_metrics.render(gauges=gauges, labeled=labeled)
    assert "apex_fleet_alive 1.0" in text
    # labels sort alphabetically; tenant (PR 13) rides every peer row
    assert ('apex_fleet_peer_up{identity="actor-0",role="actor",'
            'state="ALIVE",tenant="t0"} 1.0' in text)

    history = {"learner/loss": deque([(0, 1.0), (5, 0.5)]),
               "learner/empty": deque()}
    assert obs_metrics.scalar_tails(history) == {"learner/loss": 0.5}
