"""Checkpointing: full-bundle save/restore is bit-exact; enjoy needs no trainer.

The reference persists weights only (``origin_repo/learner.py:166-168``);
SURVEY.md §5.4 asks for the full train-state pytree.  These tests pin the
stronger contract: optimizer state, replay contents (ring + trees + cursors),
and the RNG key all round-trip, so a killed/restored learner continues on
EXACTLY the trajectory the uninterrupted one would have taken.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.config import small_test_config
from apex_tpu.training.checkpoint import (Checkpointer, config_from_meta,
                                          config_to_meta,
                                          evaluate_checkpoint, load_raw)
from apex_tpu.training.dqn import DQNTrainer


def _pure_train_steps(tr, m: int) -> None:
    """Learner-only continuation (no env interaction): the part of a resumed
    run whose bit-exactness the checkpoint alone determines."""
    for _ in range(m):
        tr.key, k = jax.random.split(tr.key)
        tr.train_state, tr.replay_state, _ = tr._train_step(
            tr.train_state, tr.replay_state, k, jnp.float32(0.5))


@pytest.mark.slow
def test_kill_restore_resume_is_bit_exact(tmp_path):
    cfg = small_test_config(capacity=256, batch_size=16, n_actors=1)
    t1 = DQNTrainer(cfg, checkpoint_dir=str(tmp_path / "ck"))
    t1.train(total_frames=300)          # past warmup; real training happened
    assert t1.steps_rate.total > 0
    path = t1.save_checkpoint()

    t2 = DQNTrainer(cfg, checkpoint_dir=str(tmp_path / "ck2"))
    t2.restore(path)                    # the "new process after a kill"
    assert t2.steps_rate.total == t1.steps_rate.total
    assert t2.ingested == t1.ingested

    _pure_train_steps(t1, 5)
    _pure_train_steps(t2, 5)
    for a, b in zip(jax.tree.leaves(t1.train_state),
                    jax.tree.leaves(t2.train_state), strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(t1.replay_state),
                    jax.tree.leaves(t2.replay_state), strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_autosave_every_save_interval(tmp_path):
    import dataclasses
    cfg = small_test_config(capacity=256, batch_size=16, n_actors=1)
    cfg = cfg.replace(learner=dataclasses.replace(cfg.learner,
                                                  save_interval=50))
    t = DQNTrainer(cfg, checkpoint_dir=str(tmp_path / "ck"))
    t.train(total_frames=200)
    assert t.checkpointer.latest_path() is not None
    _, meta = load_raw(t.checkpointer.latest_path())
    assert meta["steps"] % 50 == 0 and meta["steps"] > 0


def test_evaluate_checkpoint_without_trainer(tmp_path):
    cfg = small_test_config(capacity=256, batch_size=16, n_actors=1)
    t = DQNTrainer(cfg, checkpoint_dir=str(tmp_path / "ck"))
    t.train(total_frames=200)
    path = t.save_checkpoint()
    del t                               # nothing of the trainer survives
    score = evaluate_checkpoint(path, episodes=2, max_steps=100)
    assert np.isfinite(score) and score > 0  # CartPole reward >= episode len


@pytest.mark.slow
def test_evaluate_checkpoint_aql_family(tmp_path):
    """enjoy dispatches on the spec: AQL checkpoints rebuild AQLNetwork
    and drive Box actions — no trainer object, no family flag."""
    import dataclasses

    from apex_tpu.training.aql import AQLTrainer
    cfg = small_test_config(capacity=256, batch_size=16,
                            env_id="ApexContinuousNav-v0")
    cfg = cfg.replace(aql=dataclasses.replace(cfg.aql, propose_sample=8,
                                              uniform_sample=16))
    t = AQLTrainer(cfg, checkpoint_dir=str(tmp_path / "ck"))
    t.train(total_frames=150)
    path = t.save_checkpoint()
    del t
    score = evaluate_checkpoint(path, episodes=2, max_steps=40)
    assert np.isfinite(score)


def test_checkpointer_prunes_to_keep(tmp_path):
    ck = Checkpointer(str(tmp_path), keep=3)
    bundle = dict(x=jnp.arange(4))
    for step in (10, 20, 30, 40):
        ck.save(step, bundle, dict(step=step))
    names = sorted(p.name for p in tmp_path.glob("ckpt_*.msgpack"))
    assert names == ["ckpt_20.msgpack", "ckpt_30.msgpack",
                     "ckpt_40.msgpack"]
    assert ck.latest_path().endswith("ckpt_40.msgpack")


def test_config_meta_roundtrip():
    cfg = small_test_config(capacity=512, batch_size=64, n_actors=4)
    assert config_from_meta(config_to_meta(cfg)) == cfg


@pytest.mark.slow
def test_sharded_trainer_checkpoint_roundtrip(tmp_path):
    """dp=8: the full bundle (replicated train state + 8 sharded frame-pool
    replicas) saves, restores into a FRESH trainer, and the restored state
    drives the sharded fused step — multi-chip runs are resumable too."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from apex_tpu.training.apex import ApexTrainer

    cfg = small_test_config(capacity=512, batch_size=16, n_actors=2)
    cfg = cfg.replace(learner=dataclasses.replace(
        cfg.learner, mesh_shape=(8,)))
    t = ApexTrainer(cfg, publish_min_seconds=0.05,
                    checkpoint_dir=str(tmp_path))
    t.train(total_steps=12, max_seconds=240)
    path = t.save_checkpoint()
    saved_params = jax.device_get(t.train_state.params)
    saved_steps = t.steps_rate.total

    t2 = ApexTrainer(cfg, publish_min_seconds=0.05,
                     checkpoint_dir=str(tmp_path))
    t2.restore(path)
    assert t2.steps_rate.total == saved_steps
    restored = jax.device_get(t2.train_state.params)
    for a, b in zip(jax.tree.leaves(saved_params),
                    jax.tree.leaves(restored)):
        np.testing.assert_array_equal(a, b)

    # the restored (host-resident) state must drive the SHARDED step
    ts, rs, metrics = t2._train(t2.train_state, t2.replay_state,
                                jax.random.key(7), jnp.float32(0.5))
    assert np.isfinite(float(metrics["loss"]))


@pytest.mark.slow
def test_cli_kill_minus_nine_and_resume(tmp_path):
    """The operator drill (VERDICT A4): SIGKILL a running `--role apex`
    learner mid-run, relaunch with --restore, and the run continues from
    the newest checkpoint's step counter instead of step 0."""
    import json
    import os
    import signal
    import subprocess
    import sys
    import time

    from apex_tpu.training.checkpoint import Checkpointer, load_raw

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ckdir = str(tmp_path / "ck")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    args = [sys.executable, "-m", "apex_tpu.runtime", "--role", "apex",
            "--env-id", "ApexCartPole-v0", "--n-actors", "2",
            "--batch-size", "32", "--capacity", "2048", "--warmup", "64",
            "--save-interval", "50", "--checkpoint-dir", ckdir,
            "--max-seconds", "600"]
    proc = subprocess.Popen(args + ["--total-steps", "1000000"],
                            env=env, cwd=repo_root,
                            start_new_session=True)
    try:
        ck = Checkpointer(ckdir)
        deadline = time.monotonic() + 300
        while not ck._all() and time.monotonic() < deadline:
            time.sleep(0.5)
        assert ck._all(), "no checkpoint appeared before the kill"
    finally:
        # SIGKILL the whole session: no atexit, actor orphans die too
        os.killpg(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)

    _, meta = load_raw(ck.latest_path())
    s1 = meta["steps"]
    assert s1 >= 50

    rc = subprocess.run(args + ["--restore", "--total-steps", "120"],
                        env=env, cwd=repo_root, timeout=480).returncode
    assert rc == 0
    _, meta2 = load_raw(ck.latest_path())
    assert meta2["steps"] >= s1 + 100, (s1, meta2["steps"])


def test_enjoy_render_hooks(tmp_path):
    """Rendered enjoy (VERDICT r3 missing #5): ascii mode rasterizes pixel
    observations; save mode writes one .npy stack per episode through the
    full checkpoint-eval path."""
    import io

    from apex_tpu.training.checkpoint import evaluate_checkpoint
    from apex_tpu.utils.render import ascii_frame, make_render_hook

    # raster sanity on a synthetic frame: bright pixel -> dense glyph
    frame = np.zeros((84, 84, 1), np.uint8)
    frame[10:20, 10:20] = 255
    art = ascii_frame(frame, width=32)
    lines = art.splitlines()
    assert len(lines) >= 8 and len(lines[0]) == 32
    assert "@" in art and " " in art

    # ascii hook streams without error for pixel and vector obs
    buf = io.StringIO()
    hook = make_render_hook("ascii", stream=buf)
    hook(frame)
    hook(np.array([0.1, -0.2], np.float32))
    assert "@" in buf.getvalue() and "+0.100" in buf.getvalue()

    # save mode through a real checkpoint eval
    cfg = small_test_config(capacity=256, batch_size=16,
                            env_id="ApexCatchSmall-v0")
    trainer = DQNTrainer(cfg, checkpoint_dir=str(tmp_path))
    path = trainer.save_checkpoint()
    out = tmp_path / "frames"
    hook = make_render_hook("save", out_dir=str(out))
    score = evaluate_checkpoint(path, episodes=2, max_steps=30,
                                render_hook=hook)
    assert np.isfinite(score)
    files = sorted(out.glob("episode_*.npy"))
    assert len(files) == 2
    stack = np.load(files[0])
    assert stack.ndim == 4 and stack.shape[1:] == (42, 42, 1)


@pytest.mark.slow
def test_pixel_aql_frame_pool_checkpoint_roundtrip(tmp_path):
    """The frame-pool AQL bundle (frames ring + a_mu sidecar dict in
    FramePoolState.extras) must save and restore bit-exactly like every
    other layout."""
    import dataclasses as dc

    from apex_tpu.training.aql import AQLApexTrainer

    cfg = small_test_config(capacity=1024, batch_size=16, n_actors=1,
                            env_id="ApexCatchSmall-v0")
    cfg = cfg.replace(
        env=dc.replace(cfg.env, frame_stack=2),
        replay=dc.replace(cfg.replay, warmup=64),
        aql=dc.replace(cfg.aql, propose_sample=6, uniform_sample=3))
    t1 = AQLApexTrainer(cfg, publish_min_seconds=0.05, train_ratio=8.0,
                        checkpoint_dir=str(tmp_path))
    t1.train(total_steps=5, max_seconds=180)
    assert t1.steps_rate.total >= 5
    path = t1.save_checkpoint()

    t2 = AQLApexTrainer(cfg, publish_min_seconds=0.05,
                        checkpoint_dir=str(tmp_path))
    t2.restore(path)
    assert t2.steps_rate.total == t1.steps_rate.total
    assert t2.ingested == t1.ingested
    for a, b in zip(jax.tree.leaves(t1.replay_state),
                    jax.tree.leaves(t2.replay_state), strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(t1.train_state.params),
                    jax.tree.leaves(t2.train_state.params), strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the sidecar dict specifically survived
    np.testing.assert_array_equal(
        np.asarray(t1.replay_state.extras["a_mu"]),
        np.asarray(t2.replay_state.extras["a_mu"]))
