"""apexlint (apex_tpu/analysis) — rule fixtures, engine behavior, CLI.

Every rule has a firing (bad) and a non-firing (good) fixture: the pair IS
the rule's behavioral contract — heuristics may evolve, these pairs must
keep holding.  A self-check at the bottom asserts the repo itself lints
clean against the checked-in baseline, so the CI gate and this suite can
never drift apart.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from apex_tpu.analysis import (Baseline, all_rules, analyze_source)
from apex_tpu.analysis.cli import load_config, main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_rule(src: str, rule_id: str):
    """Findings of ONE rule on a dedented source snippet."""
    rules = {rule_id: all_rules()[rule_id]}
    findings, _ = analyze_source(textwrap.dedent(src), path="fix.py",
                                 rules=rules)
    return findings


def fires(src: str, rule_id: str) -> bool:
    return any(f.rule == rule_id for f in run_rule(src, rule_id))


# -- J001: jit without donation on step functions ---------------------------

def test_j001_fires_on_undonated_train_step():
    assert fires("""
        import jax
        class Core:
            def jit_train_step(self):
                return jax.jit(self.train_step)
        """, "J001")


def test_j001_silent_with_donation():
    assert not fires("""
        import jax
        class Core:
            def jit_train_step(self):
                return jax.jit(self.train_step, donate_argnums=(0, 1))
        """, "J001")


def test_j001_silent_on_policy_fn():
    # params are reused across calls — donation would be wrong, and the
    # rule must not demand it
    assert not fires("""
        import jax
        policy = jax.jit(make_policy_fn(model))
        act = jax.jit(policy_fn)
        """, "J001")


def test_j001_decorator_forms():
    assert fires("""
        import jax
        @jax.jit
        def fused_train_step(ts, rs, batch):
            return ts
        """, "J001")
    assert not fires("""
        from functools import partial
        import jax
        @partial(jax.jit, donate_argnums=(0, 1))
        def fused_train_step(ts, rs, batch):
            return ts
        """, "J001")


def test_j001_fires_on_ingest():
    assert fires("""
        import jax
        step = jax.jit(ingest)
        """, "J001")


# -- J002: host sync inside jitted code -------------------------------------

def test_j002_fires_on_float_in_jit():
    assert fires("""
        import jax
        @jax.jit
        def train_step(ts, batch):
            lr = float(ts.lr)
            return lr
        """, "J002")


def test_j002_fires_on_item_and_asarray():
    src = """
        import jax
        import numpy as np
        @jax.jit
        def train_step(ts, batch):
            a = ts.loss.item()
            b = np.asarray(batch)
            return a, b
        """
    got = {f.line for f in run_rule(src, "J002")}
    assert len(got) == 2


def test_j002_silent_outside_jit():
    # the host-side driver loop is ALLOWED to sync — that's its job
    assert not fires("""
        import numpy as np
        def add_step(self, q):
            return float(np.max(q))
        """, "J002")


def test_j002_silent_on_constants():
    assert not fires("""
        import jax
        @jax.jit
        def train_step(ts):
            return ts.x * float(1e-3)
        """, "J002")


def test_j002_sees_jit_call_sites_not_just_decorators():
    assert fires("""
        import jax
        def train_step(ts, batch):
            return float(ts.loss)
        step = jax.jit(train_step, donate_argnums=(0,))
        """, "J002")


def test_j002_sees_transitive_callees():
    # train_step is jitted and calls helper: helper is traced too
    assert fires("""
        import jax
        def helper(x):
            return float(x)
        def train_step(ts):
            return helper(ts.x)
        step = jax.jit(train_step)
        """, "J002")


def test_j002_sees_make_fn_factory_closures():
    # the repo convention: make_*_fn closures get jitted at call sites in
    # OTHER modules — the factory body must count as jitted scope
    assert fires("""
        def make_policy_fn(model):
            def policy(params, obs):
                return float(model.apply(params, obs))
            return policy
        """, "J002")


# -- J003: Python control flow on traced values -----------------------------

def test_j003_fires_on_param_comparison():
    assert fires("""
        import jax
        @jax.jit
        def step(x):
            if x > 0:
                return x
            return -x
        """, "J003")


def test_j003_fires_on_jnp_test():
    assert fires("""
        import jax
        import jax.numpy as jnp
        @jax.jit
        def step(mask):
            while jnp.any(mask):
                mask = update(mask)
            return mask
        """, "J003")


def test_j003_fires_on_traced_param_attribute():
    # ts.step is a field of the traced state, traced itself
    assert fires("""
        import jax
        @jax.jit
        def train_step(ts):
            if ts.step > 0:
                return ts
            return ts
        """, "J003")


def test_j003_silent_on_static_dispatch():
    # `is None` / isinstance / static-hint params are config branching
    assert not fires("""
        import jax
        @jax.jit
        def step(x, axis_name=None, mode="a"):
            if axis_name is not None:
                x = psum(x, axis_name)
            if mode == "a":
                return x
            return -x
        """, "J003")


def test_j003_silent_outside_jit():
    assert not fires("""
        def host_loop(reward):
            if reward > 0:
                return reward
        """, "J003")


# -- J004: PRNG key reuse ---------------------------------------------------

def test_j004_fires_on_double_use():
    assert fires("""
        import jax
        def f(key):
            a = jax.random.normal(key, (2,))
            b = jax.random.normal(key, (2,))
            return a + b
        """, "J004")


def test_j004_silent_after_split():
    assert not fires("""
        import jax
        def f(key):
            k1, k2 = jax.random.split(key)
            a = jax.random.normal(k1, (2,))
            b = jax.random.normal(k2, (2,))
            return a + b
        """, "J004")


def test_j004_fires_on_loop_reuse():
    assert fires("""
        import jax
        def f(key):
            out = []
            for _ in range(4):
                out.append(jax.random.normal(key, (2,)))
            return out
        """, "J004")


def test_j004_silent_on_per_iteration_split():
    assert not fires("""
        import jax
        def f(key):
            out = []
            for _ in range(4):
                key, k = jax.random.split(key)
                out.append(jax.random.normal(k, (2,)))
            return out
        """, "J004")


def test_j004_silent_on_branch_exclusive_use():
    # if/else (and early-return fall-through) arms each use the key once
    assert not fires("""
        import jax
        def f(key, discrete):
            if discrete:
                return jax.random.categorical(key, logits)
            return jax.random.normal(key, (2,))
        """, "J004")


def test_j004_silent_on_indexed_key_batch():
    assert not fires("""
        import jax
        def f(key):
            keys = jax.random.split(key, 8)
            out = []
            for i in range(8):
                out.append(jax.random.normal(keys[i], (2,)))
            return out
        """, "J004")


def test_j004_silent_on_comprehension_shadowing():
    assert not fires("""
        import jax
        def f(key, metrics):
            key, k = jax.random.split(key)
            use(k)
            return {k: float(v) for k, v in metrics.items()}
        """, "J004")


def test_j004_silent_on_numpy_generator_param():
    # `rng` is the numpy.random.Generator convention: stateful, reuse is
    # the point — only jax `key` params opt into tracking
    assert not fires("""
        def f(rng):
            a = helper(rng)
            b = helper(rng)
            return a, b
        """, "J004")


def test_j004_fires_in_nested_def_scope():
    assert fires("""
        import jax
        def outer():
            def sample(key):
                a = jax.random.normal(key, (2,))
                b = jax.random.uniform(key, (2,))
                return a + b
            return sample
        """, "J004")


def test_j004_silent_on_introspection_calls():
    # getattr/isinstance/len read type facts, not PRNG material — a
    # dtype dispatch before the single real consumption is not a reuse
    # (the sharded-plan key wrappers in training/apex.py do exactly this)
    assert not fires("""
        import jax
        def dispatch(key, sl):
            if getattr(key, "dtype", None) == "uint32":
                return key
            return sl.device_keys(key)
        """, "J004")


# -- J005: jit inside a loop ------------------------------------------------

def test_j005_fires_in_loop():
    assert fires("""
        import jax
        def run(fns, x):
            for fn in fns:
                y = jax.jit(fn)(x)
            return y
        """, "J005")


def test_j005_silent_outside_loop():
    assert not fires("""
        import jax
        def run(fn, xs):
            jfn = jax.jit(fn)
            for x in xs:
                y = jfn(x)
            return y
        """, "J005")


# -- J006: host sync inside a hot loop --------------------------------------

def test_j006_fires_on_device_get_in_loop():
    assert fires("""
        import jax
        def train_loop(pool, ts):
            while True:
                step(ts)
                params = jax.device_get(ts.params)
                pool.publish_params(1, params)
        """, "J006")


def test_j006_fires_on_block_until_ready_method_in_loop():
    assert fires("""
        def drain(chunks, ingest, rs):
            for chunk in chunks:
                rs = ingest(rs, chunk)
                rs.pos.block_until_ready()
            return rs
        """, "J006")


def test_j006_silent_outside_loop():
    assert not fires("""
        import jax
        def publish(pool, ts):
            params = jax.device_get(ts.params)
            pool.publish_params(1, params)
        """, "J006")


def test_j006_silent_in_timing_harness():
    """A loop that reads the clock is a measurement harness — timing a
    device fence is the one legitimate hot-loop sync (bench.py's rep
    loops)."""
    assert not fires("""
        import time, jax
        def measure(fn, ts, reps):
            rates = []
            for _ in range(reps):
                t0 = time.perf_counter()
                out = fn(ts)
                jax.block_until_ready(out)
                rates.append(time.perf_counter() - t0)
            return rates
        """, "J006")


def test_j006_silent_under_trace_scope():
    assert not fires("""
        import jax
        from apex_tpu.utils.profiling import trace
        def profile(fn, ts, xs):
            with trace("/tmp/prof"):
                for x in xs:
                    jax.block_until_ready(fn(ts, x))
        """, "J006")


def test_j006_silent_in_jitted_scope():
    """Inside jit it's J002's territory, not a hot-loop finding."""
    assert not fires("""
        import jax
        @jax.jit
        def step(xs):
            for x in xs:
                y = jax.device_get(x)
            return y
        """, "J006")


# -- J007: device_put inside jitted/shard_map scope -------------------------

def test_j007_fires_on_device_put_in_jit():
    assert fires("""
        import jax
        @jax.jit
        def fused_step(ts, batch):
            batch = jax.device_put(batch)
            return update(ts, batch)
        """, "J007")


def test_j007_fires_inside_shard_map_body():
    """shard_map bodies are jitted scope: the mapped per-chip fn always
    runs inside the compiled program (jit detection seeds on any
    shard_map / shard_map_compat call)."""
    assert fires("""
        import jax
        from apex_tpu.parallel.mesh import shard_map_compat
        def make_step(mesh, spec):
            def per_chip(rs, ingest):
                ingest = jax.device_put(ingest)
                return add(rs, ingest)
            return jax.jit(shard_map_compat(
                per_chip, mesh=mesh, in_specs=spec, out_specs=spec))
        """, "J007")


def test_j007_silent_on_host_side_staging():
    """The staging thread's device_put — OUTSIDE any jitted scope — is
    the sanctioned pattern the rule points at."""
    assert not fires("""
        import jax
        def stage(slot, sharding):
            return jax.tree.map(
                lambda x: jax.device_put(x, sharding), slot)
        """, "J007")


def test_j007_silent_on_unrelated_attr():
    assert not fires("""
        import jax
        @jax.jit
        def step(ts, pool):
            return pool.device_put_count
        """, "J007")


# -- J008: jitted result materialized before its use site -------------------

def test_j008_fires_on_eager_materialize_in_step_loop():
    """The exact pre-PR-4 actor anti-pattern: dispatch, block on
    np.asarray immediately, then do unrelated host work before the slot
    loop consumes the values — the sync serializes dispatch against work
    it could overlap (actors/vector.py removed this shape)."""
    assert fires("""
        import jax
        import numpy as np
        class Fam:
            def __init__(self, fn):
                self.policy = jax.jit(fn)
            def step_all(self, params, stacks, eps, key):
                out = self.policy(params, stacks, eps, key)
                actions = np.asarray(out[0])
                stats = []
                bookkeeping(stats)
                for i in range(len(stats)):
                    step_env(i, actions[i])
                return stats
        """, "J008")


def test_j008_fires_on_device_get_inside_loop():
    assert fires("""
        import jax
        step = jax.jit(fused)
        def drive(ts, chunks):
            for chunk in chunks:
                m = step(ts, chunk)
                host = jax.device_get(m)
                other_work(chunk)
                log(host)
        """, "J008")


def test_j008_silent_when_materialized_at_use_site():
    """Deferring the sync to immediately before the consuming loop is the
    sanctioned shape (the double-buffered step materializes each group
    right before stepping that group's envs)."""
    assert not fires("""
        import jax
        import numpy as np
        class Fam:
            def __init__(self, fn):
                self.policy = jax.jit(fn)
            def step_all(self, params, stacks, eps, key):
                out = self.policy(params, stacks, eps, key)
                stats = []
                bookkeeping(stats)
                actions = np.asarray(out[0])
                for i in range(len(stats)):
                    step_env(i, actions[i])
                return stats
        """, "J008")


def test_j008_silent_under_phase_timer_scope():
    """A deliberate, *accounted* wait (PhaseTimer.phase) is exempt — the
    actor families time their policy-wait there on purpose."""
    assert not fires("""
        import jax
        import numpy as np
        class Fam:
            def __init__(self, fn, timer):
                self.policy = jax.jit(fn)
                self.phase = timer
            def step_all(self, params, stacks, eps, key):
                out = self.policy(params, stacks, eps, key)
                with self.phase.phase("policy_wait"):
                    actions = np.asarray(out[0])
                bookkeeping()
                for a in actions:
                    step_env(a)
        """, "J008")


def test_j008_silent_on_plain_numpy_asarray():
    """np.asarray over host values (no jit dispatch in sight) is ordinary
    numpy code, not a device sync."""
    assert not fires("""
        import numpy as np
        def collect(rows):
            arr = np.asarray(rows)
            out = []
            normalize(out)
            for r in arr:
                out.append(r)
            return out
        """, "J008")


# -- C001: process start after a live thread --------------------------------

def test_c001_fires_on_fork_after_thread():
    assert fires("""
        import threading, multiprocessing
        def boot(w, m):
            t = threading.Thread(target=w)
            t.start()
            p = multiprocessing.Process(target=m)
            p.start()
        """, "C001")


def test_c001_silent_with_spawn_context():
    assert not fires("""
        import threading, multiprocessing as mp
        ctx = mp.get_context("spawn")
        def boot(w, m):
            t = threading.Thread(target=w)
            t.start()
            p = ctx.Process(target=m)
            p.start()
        """, "C001")


def test_c001_exactly_one_finding_not_duplicated_at_module_scope():
    findings = run_rule("""
        import threading, multiprocessing
        def boot(w, m):
            t = threading.Thread(target=w)
            t.start()
            p = multiprocessing.Process(target=m)
            p.start()
        """, "C001")
    assert len(findings) == 1


def test_c001_silent_across_separate_functions():
    # runtime order of two functions is unknowable statically
    assert not fires("""
        import threading, multiprocessing
        def a(w):
            t = threading.Thread(target=w)
            t.start()
        def b(m):
            p = multiprocessing.Process(target=m)
            p.start()
        """, "C001")


def test_c001_silent_processes_first():
    assert not fires("""
        import threading, multiprocessing
        def boot(w, m):
            p = multiprocessing.Process(target=m)
            p.start()
            t = threading.Thread(target=w)
            t.start()
        """, "C001")


# -- C002: zmq socket lifecycle ---------------------------------------------

def test_c002_fires_on_unclosed_local_socket():
    assert fires("""
        import zmq
        def send(msg):
            sock = zmq.Context.instance().socket(zmq.PUSH)
            sock.send(msg)
        """, "C002")


def test_c002_silent_when_closed():
    assert not fires("""
        import zmq
        def send(msg):
            sock = zmq.Context.instance().socket(zmq.PUSH)
            try:
                sock.send(msg)
            finally:
                sock.close(linger=0)
        """, "C002")


def test_c002_fires_on_class_socket_without_teardown():
    assert fires("""
        import zmq
        class Pub:
            def __init__(self, ctx):
                self.sock = ctx.socket(zmq.PUB)
        """, "C002")


def test_c002_silent_on_class_with_close():
    assert not fires("""
        import zmq
        class Pub:
            def __init__(self, ctx):
                self.sock = ctx.socket(zmq.PUB)
            def close(self):
                self.sock.close(linger=0)
        """, "C002")


def test_c002_silent_when_socket_escapes():
    # handed to another owner: the receiver's lifecycle problem
    assert not fires("""
        import zmq
        def make(ctx, registry):
            sock = ctx.socket(zmq.PUB)
            registry.add(sock)
        """, "C002")


# -- C003: shm created without close/unlink ---------------------------------

def test_c003_fires_on_leaked_segment():
    assert fires("""
        def make(name):
            ring = ShmRing(name, slot_size=64, n_slots=8, create=True)
            ring.push(b"x")
        """, "C003")


def test_c003_silent_when_closed():
    assert not fires("""
        def make(name):
            ring = ShmRing(name, slot_size=64, n_slots=8, create=True)
            try:
                ring.push(b"x")
            finally:
                ring.close()
        """, "C003")


def test_c003_silent_on_open_not_create():
    assert not fires("""
        def peek(name):
            ring = ShmRing(name)
            return ring.pending()
        """, "C003")


# -- C004: unlink from a non-creator ----------------------------------------

def test_c004_fires_on_foreign_unlink():
    assert fires("""
        from multiprocessing import shared_memory
        def drop(name):
            seg = shared_memory.SharedMemory(name, create=False)
            seg.unlink()
        """, "C004")


def test_c004_silent_for_creator():
    assert not fires("""
        from multiprocessing import shared_memory
        def make(name):
            seg = shared_memory.SharedMemory(name, create=True, size=64)
            seg.unlink()
        """, "C004")


def test_c004_silent_under_owner_guard():
    # ring.py contract: runtime-determined ownership gates unlink
    assert not fires("""
        class Facade:
            def __init__(self, name):
                self._ring = ShmRing(name)
            def close(self):
                if self._owner:
                    self._ring.unlink()
        """, "C004")


def test_c004_fires_on_unguarded_class_unlink():
    assert fires("""
        class Facade:
            def __init__(self, name):
                self._ring = ShmRing(name)
            def close(self):
                self._ring.unlink()
        """, "C004")


# -- C005: naked pickle loads ----------------------------------------------

def test_c005_fires_on_naked_pickle_loads():
    assert fires("""
        import pickle
        def recv(sock):
            return pickle.loads(sock.recv())
        """, "C005")


def test_c005_fires_on_unpickler_construction():
    assert fires("""
        import io, pickle
        def recv(data):
            return pickle.Unpickler(io.BytesIO(data)).load()
        """, "C005")
    assert fires("""
        import io
        from pickle import Unpickler
        def recv(data):
            return Unpickler(io.BytesIO(data)).load()
        """, "C005")


def test_c005_silent_through_restricted_wire():
    # the sanctioned path: route receives through the allowlisted module
    assert not fires("""
        from apex_tpu.runtime import wire
        def recv(sock):
            return wire.restricted_loads(sock.recv())
        """, "C005")
    # dumps (send side) and json.loads are not unpickles
    assert not fires("""
        import json, pickle
        def send(sock, msg):
            sock.send(pickle.dumps(msg))
            return json.loads(sock.recv())
        """, "C005")


def test_c005_allowlisted_module_is_exempt():
    # wire.py IS the restricted unpickler — the one place a raw
    # Unpickler may exist
    src = textwrap.dedent("""
        import pickle
        class RestrictedUnpickler(pickle.Unpickler):
            pass
        def restricted_loads(data):
            import io
            return RestrictedUnpickler(io.BytesIO(data)).load()
        """)
    rules = {"C005": all_rules()["C005"]}
    findings, _ = analyze_source(src, path="apex_tpu/runtime/wire.py",
                                 rules=rules)
    assert not findings


# -- J009: device arrays on mp queues ---------------------------------------

def test_j009_fires_on_device_result_put():
    assert fires("""
        import jax
        policy = jax.jit(policy_fn)
        def worker(params, x, chunk_queue):
            while True:
                actions, q_values = policy(params, x)
                chunk_queue.put((actions, q_values))
        """, "J009")


def test_j009_silent_with_host_materialize():
    # materialized inline at the put site
    assert not fires("""
        import jax
        import numpy as np
        policy = jax.jit(policy_fn)
        def worker(params, x, chunk_queue):
            while True:
                actions, q_values = policy(params, x)
                chunk_queue.put((int(actions[0]), np.asarray(q_values)))
        """, "J009")
    # or rebound to a host var first
    assert not fires("""
        import jax
        import numpy as np
        policy = jax.jit(policy_fn)
        def worker(params, x, stat_q):
            while True:
                q_values = policy(params, x)
                host_q = np.asarray(q_values)
                stat_q.put_nowait(host_q)
        """, "J009")


def test_j009_silent_on_host_data_and_non_queues():
    # plain host messages on queues are the normal case
    assert not fires("""
        import jax
        policy = jax.jit(policy_fn)
        def worker(chunk_queue, builder, params, x):
            a = policy(params, x)
            for msg in builder.poll():
                chunk_queue.put(("chunk", 0, msg))
        """, "J009")
    # a non-queue receiver named `sink` is out of scope
    assert not fires("""
        import jax
        policy = jax.jit(policy_fn)
        def worker(sink, params, x):
            a = policy(params, x)
            sink.put(a)
        """, "J009")


# -- J010: host clocks / obs span emission inside jitted scope ---------------

def test_j010_fires_on_clock_read_in_jitted_step():
    # the obs-plane hazard: a timestamp read inside the compiled program
    # traces to ONE frozen constant per compile
    assert fires("""
        import time
        import jax
        @jax.jit
        def fused_step(ts, rs, chunk):
            t0 = time.perf_counter()
            return update(ts, rs, chunk), t0
        """, "J010")
    assert fires("""
        import jax
        from time import monotonic
        def train_step(ts, batch):
            started = monotonic()
            return apply(ts, batch), started
        step = jax.jit(train_step)
        """, "J010")


def test_j010_fires_on_span_emission_in_jitted_scope():
    assert fires("""
        import jax
        from apex_tpu.obs import spans as obs_spans
        @jax.jit
        def fused_step(ts, rs, msg):
            stamp(msg, "consume")
            return update(ts, rs, msg)
        """, "J010")
    assert fires("""
        import jax
        @jax.jit
        def train_step(ts, batch, ring):
            ring.complete("x", 0.0, 0.1)
            return apply(ts, batch)
        """, "J010")


def test_j010_silent_on_host_loop_timing():
    # the sanctioned shape: clocks around the dispatch, on the host loop
    assert not fires("""
        import time
        import jax
        step = jax.jit(fused)
        def drive(ts, chunks):
            for chunk in chunks:
                t0 = time.perf_counter()
                ts = step(ts, chunk)
                record(time.perf_counter() - t0)
        """, "J010")
    # span stamping at the host consume site is exactly the design
    assert not fires("""
        import jax
        from apex_tpu.obs import spans as obs_spans
        step = jax.jit(fused)
        def consume(ts, slot):
            obs_spans.stamp_spans(slot.spans, "consume")
            return step(ts, slot.payload)
        """, "J010")


def test_j010_silent_on_non_time_receivers():
    # x.time() on an arbitrary receiver is not a clock read
    assert not fires("""
        import jax
        @jax.jit
        def fused_step(ts, sched):
            return ts, sched.time(3)
        """, "J010")
    # .complete on a non-ring receiver is out of scope
    assert not fires("""
        import jax
        @jax.jit
        def train_step(ts, task):
            task.complete("done", 0, 1)
            return ts
        """, "J010")


# -- J011: pjit/shard_map sharding-annotation drift --------------------------

def test_j011_fires_on_undeclared_axis_in_shard_map_specs():
    # the drift: make_mesh declares ("dp", "tp"), the step annotates "mp"
    assert fires("""
        from jax.sharding import PartitionSpec as P
        from apex_tpu.parallel.mesh import make_mesh, shard_map_compat
        mesh = make_mesh(dp=4)
        step = shard_map_compat(train, mesh=mesh,
                                in_specs=(P(), P("mp")),
                                out_specs=P("mp"))
        """, "J011")


def test_j011_fires_on_undeclared_axis_in_named_sharding():
    assert fires("""
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        mesh = Mesh(devices, ("dp", "tp"))
        sharding = NamedSharding(mesh, P("model"))
        """, "J011")


def test_j011_fires_on_fused_dp_axis_drift():
    # the PR 17 fused-plane idiom — replay state sharded over the dp
    # mesh via NamedSharding + a shard_map'd per-chip step: an axis
    # name the mesh never declared degrades every pool partition to
    # replication silently
    assert fires("""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from apex_tpu.parallel.mesh import make_mesh, shard_map_compat
        mesh = make_mesh(dp=2)
        shard = NamedSharding(mesh, P("data"))
        step = shard_map_compat(per_chip, mesh=mesh,
                                in_specs=(P(), P("data")),
                                out_specs=(P(), P("data")),
                                check_vma=False)
        """, "J011")


def test_j011_silent_on_declared_axes():
    assert not fires("""
        from jax.sharding import PartitionSpec as P
        from apex_tpu.parallel.mesh import make_mesh, shard_map_compat
        mesh = make_mesh(dp=4)
        step = shard_map_compat(train, mesh=mesh,
                                in_specs=(P(), P("dp"), P(("dp", "tp"))),
                                out_specs=P("dp"))
        """, "J011")


def test_j011_silent_without_mesh_vocabulary():
    # no mesh declared or imported: the rule cannot judge drift
    assert not fires("""
        from jax.sharding import PartitionSpec as P
        step = wrap(train, in_specs=(P("rows"),), out_specs=P("rows"))
        """, "J011")


def test_j011_silent_on_specs_outside_annotation_surfaces():
    # a P(...) passed to arbitrary helpers is not an annotation surface
    # (axis names there are that helper's business)
    assert not fires("""
        from jax.sharding import PartitionSpec as P
        from apex_tpu.parallel.mesh import make_mesh
        mesh = make_mesh(dp=4)
        layout = describe_layout(P("whatever"))
        """, "J011")


# -- J012: cross-process port collisions in one topology ---------------------

def test_j012_fires_on_duplicate_ports_in_one_config_call():
    assert fires("""
        from apex_tpu.config import CommsConfig
        comms = CommsConfig(batch_port=51001, param_port=51001)
        """, "J012")


def test_j012_fires_on_duplicate_port_defaults_in_a_config_class():
    assert fires("""
        from dataclasses import dataclass
        @dataclass(frozen=True)
        class MyComms:
            batch_port: int = 51001
            prios_port: int = 51002
            replay_port_base: int = 51001
        """, "J012")


def test_j012_silent_on_distinct_ports_and_nonport_duplicates():
    # distinct ports are the healthy topology; equal NON-port ints (hwm,
    # window sizes) are not a collision
    assert not fires("""
        from apex_tpu.config import CommsConfig
        comms = CommsConfig(batch_port=51001, param_port=52001,
                            param_hwm=3, max_outstanding_sends=3)
        """, "J012")


def test_j012_silent_on_variable_and_zero_ports():
    # test fixtures bind ephemeral ports through variables, and 0 means
    # disabled/ephemeral — neither is a literal topology
    assert not fires("""
        from apex_tpu.config import CommsConfig
        batch, param = free_ports(2)
        a = CommsConfig(batch_port=batch, param_port=param)
        b = CommsConfig(batch_port=0, param_port=0)
        """, "J012")


# -- J013: zmq socket touched from two thread-entry methods ------------------

def test_j013_fires_on_socket_shared_by_two_thread_entries():
    assert fires("""
        import threading
        import zmq
        class Bad:
            def __init__(self):
                self.sock = zmq.Context.instance().socket(zmq.ROUTER)
                self._recv = threading.Thread(target=self._recv_loop)
                self._acker = threading.Thread(target=self._ack_loop)
            def _recv_loop(self):
                while True:
                    self.sock.recv_multipart()
            def _ack_loop(self):
                while True:
                    self.sock.send(b"ack")
        """, "J013")


def test_j013_fires_through_intra_class_helper_calls():
    # the touch lives in a helper; both thread entries reach it through
    # the class-local call graph — still two threads on one socket
    assert fires("""
        import threading
        import zmq
        class Bad:
            def __init__(self, ctx):
                self.sock = ctx.socket(zmq.DEALER)
                threading.Thread(target=self._a).start()
                threading.Thread(target=self._b).start()
            def _flush(self):
                self.sock.send(b"x")
            def _a(self):
                self._flush()
            def _b(self):
                self._flush()
        """, "J013")


def test_j013_silent_on_queue_handoff_pattern():
    # the ChunkReceiver shape: decoders enqueue acks, ONE socket thread
    # drains the queue and touches the socket — single-owner, clean
    assert not fires("""
        import queue
        import threading
        import zmq
        class Good:
            def __init__(self):
                self.sock = zmq.Context.instance().socket(zmq.ROUTER)
                self._ack_q = queue.Queue()
                self._recv = threading.Thread(target=self._run)
                self._decoders = [threading.Thread(target=self._decode)
                                  for _ in range(4)]
            def _run(self):
                while True:
                    self.sock.recv_multipart()
                    ident = self._ack_q.get_nowait()
                    self.sock.send_multipart([ident, b"ack"])
            def _decode(self):
                while True:
                    self._ack_q.put(b"peer")
        """, "J013")


def test_j013_silent_on_single_thread_and_main_thread_teardown():
    # one thread entry owning the socket + main-thread stop()/close() is
    # the documented migrate-then-use pattern, not a race the rule flags
    assert not fires("""
        import threading
        import zmq
        class Good:
            def __init__(self):
                self.sock = zmq.Context.instance().socket(zmq.REP)
                self._thread = threading.Thread(target=self._serve)
            def _serve(self):
                while True:
                    self.sock.recv()
                    self.sock.send(b"ok")
            def stop(self):
                self.sock.close(linger=0)
        """, "J013")


def test_j013_silent_on_two_threads_two_sockets():
    assert not fires("""
        import threading
        import zmq
        class Good:
            def __init__(self, ctx):
                self.rx = ctx.socket(zmq.PULL)
                self.tx = ctx.socket(zmq.PUSH)
                threading.Thread(target=self._rx_loop).start()
                threading.Thread(target=self._tx_loop).start()
            def _rx_loop(self):
                self.rx.recv()
            def _tx_loop(self):
                self.tx.send(b"x")
        """, "J013")


# -- J014: host numpy op in a lax.scan-scanned env/rollout body --------------

def test_j014_fires_on_np_in_scan_body():
    assert fires("""
        import jax
        import numpy as np
        def rollout(state, keys):
            def body(carry, key):
                pos = np.clip(carry + 1, 0, 10)
                return pos, pos
            return jax.lax.scan(body, state, keys)
        """, "J014")


def test_j014_fires_through_lambda_and_method_closure():
    # the anakin shape: lax.scan(lambda c, x: self._step(...)) — the
    # method and its callees are scanned scope via the call graph
    assert fires("""
        import jax
        import numpy as np
        class Engine:
            def _flush(self, c):
                return np.concatenate([c, c])
            def _step(self, c, x):
                return self._flush(c), x
            def _dispatch(self, c, xs):
                return jax.lax.scan(lambda cc, x: self._step(cc, x),
                                    c, xs)
        """, "J014")


def test_j014_fires_on_float_and_item():
    assert fires("""
        import jax
        def rollout(state, keys):
            def body(carry, key):
                r = float(carry)
                return carry, r
            return jax.lax.scan(body, state, keys)
        """, "J014")
    assert fires("""
        import jax
        def rollout(state, keys):
            def body(carry, key):
                return carry, carry.item()
            return jax.lax.scan(body, state, keys)
        """, "J014")


def test_j014_silent_outside_scan_and_on_static_args():
    # np on the host side of the dispatch is the NORMAL pattern
    assert not fires("""
        import jax
        import numpy as np
        def host_convert(out):
            return np.asarray(out)
        def rollout(state, keys):
            def body(carry, key):
                return carry + 1, carry
            return jax.lax.scan(body, state, keys)
        """, "J014")
    # static shape/config construction at trace time is legitimate
    assert not fires("""
        import jax
        import numpy as np
        class Engine:
            def _step(self, c, x):
                d = np.prod(self.frame_shape)
                ar = np.arange(self.B)
                return c, d
            def _dispatch(self, c, xs):
                return jax.lax.scan(lambda cc, x: self._step(cc, x),
                                    c, xs)
        """, "J014")


def test_j014_silent_on_jnp_in_scan_body():
    assert not fires("""
        import jax
        import jax.numpy as jnp
        def rollout(state, keys):
            def body(carry, key):
                return jnp.clip(carry + 1, 0, 10), carry
            return jax.lax.scan(body, state, keys)
        """, "J014")


# -- J015: literal gauge/family names outside the metric registry ------------

def test_j015_fires_on_unregistered_heartbeat_gauge_key():
    findings = run_rule("""
        from apex_tpu.fleet.heartbeat import Heartbeat
        def beat():
            return Heartbeat("infer-0", gauges={"queue_depth": 1,
                                                "totally_novel_gauge": 2})
        """, "J015")
    assert len(findings) == 1
    assert "totally_novel_gauge" in findings[0].message


def test_j015_fires_on_gauges_fn_lambda_and_named_hook():
    # the run_loadgen shape: gauges_fn=lambda returning a literal dict
    assert fires("""
        from apex_tpu.fleet.heartbeat import HeartbeatEmitter
        def loop():
            beat = HeartbeatEmitter(
                "loadgen-0", gauges_fn=(lambda: {"bogus_counter": 1}))
        """, "J015")
    # the anakin shape: gauges_fn=self.method, method returns a literal
    assert fires("""
        from apex_tpu.fleet.heartbeat import HeartbeatEmitter
        class Pool:
            def my_counters(self):
                return {"not_in_registry": 3}
            def start(self):
                self.hb = HeartbeatEmitter(
                    "x", gauges_fn=self.my_counters)
        """, "J015")


def test_j015_fires_on_unregistered_exposition_family():
    assert fires("""
        from apex_tpu.obs.metrics import render
        def expo():
            labeled = {"my_adhoc_family": [({"x": "y"}, 1.0)]}
            return render(labeled=labeled)
        """, "J015")


def test_j015_silent_on_registered_keys_and_dynamic_names():
    # every key declared in the registry: the normal emitter shape
    assert not fires("""
        from apex_tpu.fleet.heartbeat import Heartbeat
        def beat(depth):
            return Heartbeat("infer-0", gauges={"queue_depth": depth,
                                                "batch_p50": 1.5,
                                                "infer_rt_ms_p99": 2.0})
        """, "J015")
    # dynamic keys are not literal dataflow — scalar tails, per-peer
    # dicts, comprehensions all pass through untouched
    assert not fires("""
        from apex_tpu.obs.metrics import render
        def expo(history):
            gauges = {tag: dq[-1] for tag, dq in history.items()}
            counters = dict(build_counters())
            return render(gauges=gauges, counters=counters)
        """, "J015")


def test_j015_silent_on_gauge_keys_in_plain_dicts():
    # a dict literal that never flows into a gauges/exposition sink is
    # just a dict — the rule follows sinks, not spellings
    assert not fires("""
        def stats():
            return {"anything_goes_here": 1, "free_form": 2}
        """, "J015")


# -- J016: raw epoch/version ordering outside the fencing helpers ------------

def test_j016_fires_on_raw_epoch_ordering():
    # the replay-shard shape: an attribute epoch ordered against a local
    assert fires("""
        class Shard:
            def write_back(self, epoch):
                if epoch < self.learner_epoch:
                    return False
        """, "J016")
    # param_version too, and bare names count as well as attributes
    assert fires("""
        def gate(incoming, param_version):
            return incoming.param_version >= param_version
        """, "J016")


def test_j016_silent_on_equality_literals_and_fence_module():
    # identity checks are not ordering — fencing only cares about </>
    assert not fires("""
        class Shard:
            def seen(self, epoch):
                return epoch == self.learner_epoch
        """, "J016")
    # ordering against a LITERAL (test progress assertions like
    # `param_version >= 2`) cannot smuggle a dead life's value
    assert not fires("""
        def check(trainer):
            assert trainer.param_version >= 2
            assert trainer.learner_epoch > 0
        """, "J016")
    # THE fencing helper module is the one place raw ordering lives
    src = textwrap.dedent("""
        def newer_epoch(epoch, learner_epoch):
            return epoch > learner_epoch
        """)
    findings, _ = analyze_source(
        src, path="apex_tpu/serving/fence.py",
        rules={"J016": all_rules()["J016"]})
    assert not findings


def test_j016_fires_on_epoch_vs_version_cross_compare():
    # the exact wrong-lifetime hazard: ordering a version against an
    # epoch variable as if they shared a scale
    assert fires("""
        def promote(reply, server):
            if reply.learner_epoch >= server.param_version:
                return True
        """, "J016")


# -- J017: tenant-qualified id construction outside tenancy/namespace --------

def test_j017_fires_on_fstring_and_concat_and_join():
    # the qualified-identity shape: tenant joined to a base with "/"
    assert fires("""
        def route(tenant, actor_id):
            return f"{tenant}/actor-{actor_id}"
        """, "J017")
    # the topic shape: tenant between the apxt/ head and the | tail
    assert fires("""
        def topic(tenant):
            return "apxt/" + tenant + "|"
        """, "J017")
    # join and format spellings of the same construction
    assert fires("""
        def ident(spec_tenant, base):
            return "/".join([spec_tenant, base])
        """, "J017")
    assert fires("""
        def ident(spec, base):
            return "{}/{}".format(spec.tenant, base)
        """, "J017")


def test_j017_silent_on_logs_helpers_and_namespace_module():
    # a log line MENTIONING a tenant is not an id — no separator join
    assert not fires("""
        def log(tenant, n):
            print(f"tenant {tenant} admitted ({n} shards)")
        """, "J017")
    # routing through the namespacing helpers is the fix, not a finding
    assert not fires("""
        from apex_tpu.tenancy import namespace
        def route(tenant, base):
            return namespace.qualify(tenant, base)
        """, "J017")
    # a "/" elsewhere in the f-string (not adjacent to the tenant hole)
    # is not a qualified-id join
    assert not fires("""
        def log(tenant, a, b):
            print(f"shards {a}/{b} assigned to tenant {tenant}")
        """, "J017")
    # THE namespacing module is the one construction site
    src = textwrap.dedent("""
        def qualify(tenant, base):
            return f"{tenant}/{base}"
        """)
    findings, _ = analyze_source(
        src, path="apex_tpu/tenancy/namespace.py",
        rules={"J017": all_rules()["J017"]})
    assert not findings


def test_j017_one_finding_per_concat_chain():
    src = """
        def topic(tenant):
            return "apxt/" + tenant + "|"
        """
    assert len(run_rule(src, "J017")) == 1


# -- J018: replay residency/quota accounting outside the shard core ----------

def test_j018_fires_on_handrolled_residency_and_raw_quota_compare():
    # the resident() shape hand-rolled: residency saturates at ring
    # capacity, and a scattered min() is how two planes drift
    assert fires("""
        def admitted(core):
            return min(core.ingested, core.capacity)
        """, "J018")
    assert fires("""
        class Gate:
            def room(self):
                return min(self.ingested, self.replay.capacity)
        """, "J018")
    # quota judged against raw cumulative ingest: wrong once the ring
    # wraps (ingested grows forever, residency stopped at capacity)
    assert fires("""
        class Gate:
            def over(self):
                return self.ingested >= self.quota
        """, "J018")
    assert fires("""
        def over(core, spec):
            return core.ingested > spec.replay_quota
        """, "J018")


def test_j018_silent_on_accessors_literals_and_shard_module():
    # routing through the core's accessors is the fix, not a finding
    assert not fires("""
        def over(core):
            return core.resident() >= core.quota
        """, "J018")
    assert not fires("""
        def over(core):
            return core.over_quota()
        """, "J018")
    # ordering against literals (test progress asserts) is not
    # accounting; min() of unrelated names is just math
    assert not fires("""
        def check(core):
            assert core.ingested >= 100
            return min(1.0, core.ingested / 500)
        """, "J018")
    # equality is identity, not accounting
    assert not fires("""
        def same(core, spec):
            return core.quota == spec.replay_quota
        """, "J018")
    # THE accounting module is the one place residency math lives
    src = textwrap.dedent("""
        class ReplayShardCore:
            def resident(self):
                return min(self.ingested, self.replay.capacity)

            def over_quota(self):
                return self.quota > 0 and self.resident() >= self.quota
        """)
    findings, _ = analyze_source(
        src, path="apex_tpu/replay_service/shard.py",
        rules={"J018": all_rules()["J018"]})
    assert not findings


# -- engine: parse errors, suppressions, baseline ---------------------------

def test_parse_error_is_a_finding():
    findings, _ = analyze_source("def broken(:\n", path="x.py")
    assert [f.rule for f in findings] == ["E001"]


def test_inline_suppression_with_justification():
    src = textwrap.dedent("""
        import jax
        def f(key):
            a = jax.random.normal(key, (2,))
            b = jax.random.normal(key, (2,))  # apexlint: disable=J004 -- deliberate same-draw
            return a + b
        """)
    findings, suppressed = analyze_source(src, path="x.py")
    assert not any(f.rule == "J004" for f in findings)
    assert any(f.rule == "J004" for f in suppressed)


def test_standalone_suppression_covers_next_line():
    src = textwrap.dedent("""
        import jax
        def f(key):
            a = jax.random.normal(key, (2,))
            # apexlint: disable=J004 -- deliberate same-draw
            b = jax.random.normal(key, (2,))
            return a + b
        """)
    findings, suppressed = analyze_source(src, path="x.py")
    assert not any(f.rule == "J004" for f in findings)
    assert len(suppressed) == 1


def test_suppression_is_rule_scoped():
    # a J004 disable must NOT hide a J002 on the same line
    src = textwrap.dedent("""
        import jax
        @jax.jit
        def train_step(ts, key):
            a = jax.random.normal(key, (2,))
            b = float(jax.random.normal(key, (2,)).sum())  # apexlint: disable=J004
            return a, b
        """)
    findings, _ = analyze_source(src, path="x.py")
    assert any(f.rule == "J002" for f in findings)
    assert not any(f.rule == "J004" for f in findings)


def test_baseline_partition_and_staleness():
    src = textwrap.dedent("""
        import jax
        def f(key):
            a = jax.random.normal(key, (2,))
            b = jax.random.normal(key, (2,))
            return a + b
        """)
    findings, _ = analyze_source(src, path="m.py")
    assert findings
    base = Baseline.from_findings(findings)
    new, matched, stale = base.partition(findings)
    assert not new and matched and not stale
    # fixed code -> the entry goes stale (strict mode fails on it)
    new, matched, stale = base.partition([])
    assert not new and not matched and stale


def test_baseline_line_number_drift_still_matches():
    src = ("import jax\n"
           "def f(key):\n"
           "    a = jax.random.normal(key, (2,))\n"
           "    b = jax.random.normal(key, (2,))\n"
           "    return a + b\n")
    findings, _ = analyze_source(src, path="m.py")
    base = Baseline.from_findings(findings)
    shifted, _ = analyze_source("# new header comment\n" + src, path="m.py")
    new, matched, stale = base.partition(shifted)
    assert not new and matched and not stale


# -- J019: learner state mutated from a FleetStatusServer hook ---------------

def test_j019_fires_on_state_mutation_in_ctl_hook():
    # the anti-pattern the rule exists for: the ctl hook applies the
    # weight copy on the status-server thread, racing the hot loop
    assert fires("""
        class Trainer:
            def _serve(self):
                self._fleet_status = FleetStatusServer(
                    comms, self.fleet, ctl_fn=self._on_ctl)

            def _on_ctl(self, cmd):
                self.train_state = self._load(cmd["path"])
                return {"accepted": True}
        """, "J019")
    # calling a trainer-thread applier from the hook is the same race
    assert fires("""
        class Trainer:
            def _serve(self):
                self._fleet_status = FleetStatusServer(
                    comms, self.fleet, ctl_fn=self._on_ctl)

            def _on_ctl(self, cmd):
                self.restore_weights(cmd["path"])
                return {"accepted": True}
        """, "J019")
    # one level of same-class delegation is followed
    assert fires("""
        class Trainer:
            def _serve(self):
                self._fleet_status = FleetStatusServer(
                    comms, self.fleet, snapshot_fn=self._snap)

            def _snap(self):
                return self._refresh()

            def _refresh(self):
                self.replay_state = self._rebuild()
                return {}
        """, "J019")
    # lambda hooks are inspected inline
    assert fires("""
        class Trainer:
            def _serve(self):
                self._fleet_status = FleetStatusServer(
                    comms, self.fleet,
                    ctl_fn=lambda cmd: self.apply_hparams(cmd))
        """, "J019")


def test_j019_silent_on_enqueue_and_drain_pattern():
    # the PR 14 contract: the hook ENQUEUES only; the trainer thread
    # drains on its health tick — reads and queue puts are fine
    assert not fires("""
        class Trainer:
            def _serve(self):
                self._fleet_status = FleetStatusServer(
                    comms, self.fleet, ctl_fn=self._enqueue,
                    metrics_fn=self._metrics, snapshot_fn=self._snap)

            def _enqueue(self, cmd):
                try:
                    self._ctl_queue.put_nowait(dict(cmd))
                except Exception:
                    return {"accepted": False}
                return {"accepted": True, "pending": self._ctl_queue.qsize()}

            def _metrics(self):
                return render(gauges=dict(steps=self.steps_rate.total))

            def _snap(self):
                snap = self.fleet.snapshot()
                snap["metrics"]["learner_epoch"] = self.learner_epoch
                return snap
        """, "J019")
    # state mutation on the TRAINER thread (no hook involvement) is the
    # correct half of the pattern, not a finding
    assert not fires("""
        class Trainer:
            def _drain(self, steps):
                cmd = self._ctl_queue.get_nowait()
                self.train_state = self._load(cmd["path"])
        """, "J019")


# -- CLI --------------------------------------------------------------------

def _write(tmp_path, name, content):
    p = tmp_path / name
    p.write_text(textwrap.dedent(content))
    return str(p)


def test_cli_exit_codes_and_json(tmp_path, capsys):
    bad = _write(tmp_path, "bad.py", """
        import jax
        def f(key):
            a = jax.random.normal(key, (2,))
            b = jax.random.normal(key, (2,))
            return a + b
        """)
    assert main([bad, "--no-baseline", "--json"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["summary"]["new"] == 1
    assert out["findings"][0]["rule"] == "J004"

    good = _write(tmp_path, "good.py", "x = 1\n")
    assert main([good, "--no-baseline"]) == 0
    assert main(["--list-rules"]) == 0
    assert main([str(tmp_path / "missing.py")]) == 2
    assert main([bad, "--disable", "NOPE"]) == 2
    assert main([bad, "--no-baseline", "--disable", "J004"]) == 0


def test_cli_write_baseline_roundtrip(tmp_path, capsys):
    bad = _write(tmp_path, "bad.py", """
        import jax
        def f(key):
            a = jax.random.normal(key, (2,))
            b = jax.random.normal(key, (2,))
            return a + b
        """)
    base = str(tmp_path / "base.json")
    assert main([bad, "--baseline", base, "--write-baseline"]) == 0
    assert main([bad, "--baseline", base]) == 0          # accepted
    capsys.readouterr()


def test_pyproject_config_is_read():
    cfg = load_config(REPO)
    assert "apex_tpu" in cfg.get("paths", [])
    assert cfg.get("baseline") == ".apexlint-baseline.json"


def test_every_rule_has_registry_metadata():
    rules = all_rules()
    assert {"J001", "J002", "J003", "J004", "J005",
            "C001", "C002", "C003", "C004"} <= set(rules)
    for rid, rule in rules.items():
        assert rule.id == rid and rule.name and rule.description


# -- self-check: the repo lints clean against its baseline ------------------

def test_repo_lints_clean_strict():
    """The merge gate: zero unsuppressed findings, zero stale baseline
    entries, over the configured [tool.apexlint] scope — exactly what CI
    runs.  A subprocess so the CLI path (module main, config discovery,
    baseline load) is exercised end to end."""
    proc = subprocess.run(
        [sys.executable, "-m", "apex_tpu.analysis", "--strict"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_acceptance_command_package_scope():
    """`python -m apex_tpu.analysis apex_tpu/` exits 0 (the README/issue
    invocation): the package itself carries zero findings, with no
    baseline help needed."""
    proc = subprocess.run(
        [sys.executable, "-m", "apex_tpu.analysis", "apex_tpu",
         "--no-baseline"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr

# -- J020: donation aliasing (whole-program dataflow) -----------------------

def test_j020_fires_on_post_dispatch_read():
    assert fires("""
        import jax

        class Learner:
            def __init__(self, step):
                self._step = jax.jit(step, donate_argnums=(0,))

            def run(self, batch):
                out = self._step(self.train_state, batch)
                return float(self.train_state.loss)
        """, "J020")


def test_j020_silent_on_rebind_epilogue():
    assert not fires("""
        import jax

        class Learner:
            def __init__(self, step):
                self._step = jax.jit(step, donate_argnums=(0,))

            def run(self, batch):
                self.train_state, metrics = self._step(self.train_state,
                                                       batch)
                return metrics
        """, "J020")


def test_j020_fires_on_loop_carried_redispatch():
    found = run_rule("""
        import jax

        class Learner:
            def __init__(self, step):
                self._step = jax.jit(step, donate_argnums=(0,))

            def run(self, batches):
                metrics = None
                for b in batches:
                    metrics = self._step(self.train_state, b)
                return metrics
        """, "J020")
    assert found and "loop iteration" in found[0].message


def test_j020_silent_when_loop_rebinds():
    assert not fires("""
        import jax

        class Learner:
            def __init__(self, step):
                self._step = jax.jit(step, donate_argnums=(0,))

            def run(self, batches):
                for b in batches:
                    self.train_state, m = self._step(self.train_state, b)
                return m
        """, "J020")


def test_j020_tracks_decorated_and_factory_donation():
    # @partial decoration and factory-returned jits both register
    assert fires("""
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, batch):
            return state

        def drive(state, batch):
            out = step(state, batch)
            return state.params
        """, "J020")
    assert fires("""
        import jax

        def make(step):
            return jax.jit(step, donate_argnums=(0,))

        class T:
            def __init__(self, step):
                self._train = make(step)

            def run(self, batch):
                out = self._train(self.train_state, batch)
                return self.train_state
        """, "J020")


def test_j020_silent_on_undonated_jit():
    assert not fires("""
        import jax

        class Learner:
            def __init__(self, step):
                self._step = jax.jit(step)

            def run(self, batch):
                out = self._step(self.train_state, batch)
                return float(self.train_state.loss)
        """, "J020")


# -- J021: band membership --------------------------------------------------

def test_j021_fires_on_raw_crc32_shard_arith():
    assert fires("""
        import zlib

        def route(identity, n_shards):
            return zlib.crc32(identity.encode()) % n_shards
        """, "J021")


def test_j021_fires_on_wrapped_hash_of_identity():
    assert fires("""
        def route(tenant_id, n):
            return abs(hash(tenant_id)) % n
        """, "J021")


def test_j021_silent_on_constant_modulus_and_round_robin():
    # seed masks / range clamps use literal moduli; round-robin isn't a hash
    assert not fires("""
        import zlib

        def seed_of(name):
            return zlib.crc32(name.encode()) % 2 ** 31
        """, "J021")
    assert not fires("""
        class S:
            def pick(self, n_shards):
                self._seq += 1
                return self._seq % n_shards
        """, "J021")


def test_j021_exempts_the_tenancy_namespace_module():
    src = textwrap.dedent("""
        import zlib

        def shard_in_band(identity, band):
            return band[zlib.crc32(identity.encode()) % len(band)]
        """)
    rules = {"J021": all_rules()["J021"]}
    findings, _ = analyze_source(src, path="apex_tpu/tenancy/namespace.py",
                                 rules=rules)
    assert not findings
    findings, _ = analyze_source(src, path="elsewhere.py", rules=rules)
    assert findings


# -- J022: fence ordering ---------------------------------------------------

def test_j022_fires_on_handbuilt_fence_tuple():
    found = run_rule("""
        class Server:
            def snapshot(self):
                return (self.learner_epoch, self.param_version)
        """, "J022")
    assert found and "fence" in found[0].message
    # transposed pairs are the same hazard (that's the point)
    assert fires("""
        def key(st):
            return (st.param_version, st.learner_epoch)
        """, "J022")


def test_j022_silent_on_parallel_assign_snapshot():
    assert not fires("""
        class Server:
            def read(self):
                pv, epoch = self.param_version, self.learner_epoch
                return pv
        """, "J022")


def test_j022_silent_on_non_fence_tuples_and_fence_module():
    assert not fires("""
        def f(st):
            return (st.learner_epoch, st.other)
        """, "J022")
    src = textwrap.dedent("""
        def fence_key(st):
            return (st.learner_epoch, st.param_version)
        """)
    findings, _ = analyze_source(src, path="apex_tpu/serving/fence.py",
                                 rules={"J022": all_rules()["J022"]})
    assert not findings


# -- J023: codec outside the codec module -----------------------------------

def test_j023_fires_on_raw_zlib_compress_of_payload():
    assert fires("""
        import zlib

        def ship(sock, payload):
            sock.send(zlib.compress(payload))
        """, "J023")
    assert fires("""
        import zlib

        def unship(blob):
            return zlib.decompress(blob)
        """, "J023")


def test_j023_fires_on_handrolled_frame_xor_delta():
    assert fires("""
        import numpy as np

        def delta(frames):
            return frames[1:] ^ frames[:-1]
        """, "J023")
    assert fires("""
        import numpy as np

        def delta(frames, prev):
            return np.bitwise_xor(frames, prev)
        """, "J023")


def test_j023_silent_on_checksums_and_seed_xor():
    # crc32/adler32 are checksums, not compression (J021 owns hash
    # routing) — and XOR over seeds/identities is arithmetic, not a codec
    assert not fires("""
        import zlib

        def route(identity, band):
            return band[zlib.crc32(identity.encode()) % len(band)]
        """, "J023")
    assert not fires("""
        import zlib

        class Chaos:
            def rng(self):
                return self.seed ^ zlib.crc32(self.identity.encode())
        """, "J023")


def test_j023_exempts_the_codec_module():
    src = textwrap.dedent("""
        import zlib

        def _frames_encode(frames):
            return zlib.compress(frames.tobytes())
        """)
    rules = {"J023": all_rules()["J023"]}
    findings, _ = analyze_source(src, path="apex_tpu/runtime/codec.py",
                                 rules=rules)
    assert not findings
    findings, _ = analyze_source(src, path="elsewhere.py", rules=rules)
    assert findings


# -- C006: cross-module thread affinity -------------------------------------

_C006_READER = """
    import jax

    class Engine:
        @jax.jit
        def step(self, x):
            return x + self.core
    """


def _c006_run(tmp_path, ctl_src):
    from apex_tpu.analysis import analyze_paths
    (tmp_path / "ctl.py").write_text(textwrap.dedent(ctl_src))
    (tmp_path / "engine.py").write_text(textwrap.dedent(_C006_READER))
    rules = {"C006": all_rules()["C006"]}
    findings, _ = analyze_paths([str(tmp_path)], rules=rules,
                                root=str(tmp_path))
    return findings


def test_c006_fires_on_thread_reachable_unlocked_mutation(tmp_path):
    found = _c006_run(tmp_path, """
        import threading

        class Ctl:
            def start(self):
                self.t = threading.Thread(target=self._loop)
                self.t.start()

            def _loop(self):
                self.core = None
        """)
    assert [f.rule for f in found] == ["C006"]
    assert "engine.py" in found[0].message


def test_c006_silent_under_lock_and_off_thread(tmp_path):
    assert not _c006_run(tmp_path, """
        import threading

        class Ctl:
            def start(self):
                self.t = threading.Thread(target=self._loop)
                self.t.start()

            def _loop(self):
                with self._state_lock:
                    self.core = None
        """)
    # same mutation NOT reachable from a Thread spawn: trainer-thread code
    assert not _c006_run(tmp_path, """
        class Ctl:
            def reset(self):
                self.core = None
        """)


def test_c006_needs_the_project_context():
    # lone-snippet analysis has no cross-module view: the rule stays quiet
    assert not fires("""
        import threading

        class Ctl:
            def start(self):
                threading.Thread(target=self._loop).start()

            def _loop(self):
                self.core = None
        """, "C006")


# -- ProjectContext: graphs and dataflow ------------------------------------

def test_project_context_import_and_call_graphs():
    from apex_tpu.analysis.graph import ProjectContext
    proj = ProjectContext({
        "pkg/__init__.py": "",
        "pkg/a.py": "from pkg.b import helper\n\n"
                    "def run():\n    return helper()\n",
        "pkg/b.py": "def helper():\n    return 1\n",
    })
    assert "pkg.b" in proj.import_graph["pkg.a"]
    assert "pkg.b.helper" in proj.call_graph["pkg.a.run"]
    assert "pkg.b.helper" in proj.definitions


def test_project_context_thread_reachability():
    from apex_tpu.analysis.graph import ProjectContext
    proj = ProjectContext({
        "m.py": textwrap.dedent("""
            import threading

            def work():
                helper()

            def helper():
                pass

            def main():
                threading.Thread(target=work).start()
            """),
    })
    assert "m.work" in proj.thread_targets
    # the closure follows call-graph edges out of the spawn target
    assert {"m.work", "m.helper"} <= proj.thread_reachable
    assert "m.main" not in proj.thread_reachable


def test_reaching_defs_branch_union_and_params():
    import ast as _a

    from apex_tpu.analysis.dataflow import reaching_defs
    fn = _a.parse(textwrap.dedent("""
        def f(x, cond):
            y = x + 1
            if cond:
                y = 2
            return y
        """)).body[0]
    defs = reaching_defs(fn)
    ret_y = [n for n in defs if n.id == "y"]
    assert ret_y and len(defs[ret_y[-1]]) == 2      # both branches reach
    x_loads = [n for n in defs if n.id == "x"]
    assert x_loads and defs[x_loads[0]] == {fn}     # params reach as fn


def test_donated_callables_resolves_bindings_and_factories():
    from apex_tpu.analysis.core import ModuleContext
    from apex_tpu.analysis.dataflow import donated_callables
    ctx = ModuleContext("m.py", textwrap.dedent("""
        import jax

        def make(step):
            return jax.jit(step, donate_argnums=(0, 1))

        class T:
            def __init__(self, step):
                self._step = jax.jit(step, donate_argnums=(0,))
                self._train = make(step)
        """))
    d = donated_callables(ctx)
    assert d["self._step"].positions == (0,)
    assert d["self._train"].positions == (0, 1)


# -- SARIF artifact ---------------------------------------------------------

def test_sarif_report_shape(tmp_path, capsys):
    bad = _write(tmp_path, "bad.py", """
        import jax
        def f(key):
            a = jax.random.normal(key, (2,))
            b = jax.random.normal(key, (2,))
            return a + b
        """)
    sarif = tmp_path / "out.sarif"
    assert main([bad, "--no-baseline", "--sarif", str(sarif)]) == 1
    capsys.readouterr()
    doc = json.loads(sarif.read_text())
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-2.1.0.json")
    run = doc["runs"][0]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"J001", "J004", "J020", "J021", "J022", "J023", "C006"} <= rule_ids
    res = [r for r in run["results"] if r["ruleId"] == "J004"]
    assert res and res[0]["level"] == "error"
    loc = res[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("bad.py")
    assert loc["region"]["startLine"] > 0


def test_sarif_baselined_findings_are_suppressed_notes(tmp_path, capsys):
    bad = _write(tmp_path, "bad.py", """
        import jax
        def f(key):
            a = jax.random.normal(key, (2,))
            b = jax.random.normal(key, (2,))
            return a + b
        """)
    base = str(tmp_path / "base.json")
    assert main([bad, "--baseline", base, "--write-baseline"]) == 0
    sarif = tmp_path / "out.sarif"
    assert main([bad, "--baseline", base, "--sarif", str(sarif)]) == 0
    capsys.readouterr()
    run = json.loads(sarif.read_text())["runs"][0]
    res = [r for r in run["results"] if r["ruleId"] == "J004"]
    assert res and res[0]["level"] == "note"
    assert res[0]["suppressions"][0]["kind"] == "external"


# -- config reader ----------------------------------------------------------

def test_config_multiline_array_with_comments(tmp_path):
    # regression: a per-item comment used to truncate the folded buffer
    # at its '#' and silently drop the whole key
    (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
        [tool.apexlint]
        paths = [
            "apex_tpu",     # the package
            "tests",        # and its tests
        ]
        baseline = ".apexlint-baseline.json"
        disable = []

        [tool.other]
        x = "[not # ours]"
        """))
    cfg = load_config(str(tmp_path))
    assert cfg["paths"] == ["apex_tpu", "tests"]
    assert cfg["baseline"] == ".apexlint-baseline.json"
    assert cfg["disable"] == []


def test_config_bad_values_complain_loudly(tmp_path, capsys):
    (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
        [tool.apexlint]
        paths = not-a-value (
        baseline = ".ok.json"
        """))
    cfg = load_config(str(tmp_path))
    err = capsys.readouterr().err
    assert "paths" in err and "ignored" in err
    assert cfg.get("baseline") == ".ok.json"    # later keys still parse


def test_config_unterminated_array_complains(tmp_path, capsys):
    (tmp_path / "pyproject.toml").write_text(
        "[tool.apexlint]\ndisable = [\n    \"J001\",\n")
    cfg = load_config(str(tmp_path))
    assert "disable" not in cfg
    assert "unterminated" in capsys.readouterr().err


def test_config_hash_inside_quoted_value_survives(tmp_path):
    (tmp_path / "pyproject.toml").write_text(
        '[tool.apexlint]\nbaseline = "base#1.json"  # real comment\n')
    assert load_config(str(tmp_path))["baseline"] == "base#1.json"


# -- catalog / explain ------------------------------------------------------

def test_catalog_covers_every_rule_with_why_and_fix():
    from apex_tpu.analysis import catalog
    entries = {e["id"]: e for e in catalog()}
    assert set(entries) == set(all_rules())
    for e in entries.values():
        assert e["why"] and e["fix"], e["id"]


def test_explain_prints_why_and_fix(capsys):
    assert main(["--explain", "J021"]) == 0
    out = capsys.readouterr().out
    assert "J021" in out and "why:" in out and "fix:" in out
    assert main(["--explain", "NOPE"]) == 2
    capsys.readouterr()


def test_readme_rule_table_is_generated(capsys):
    """The README's rule table is the catalog_markdown() output verbatim
    (between the apexlint-catalog markers) — regenerate it with
    `python -m apex_tpu.analysis --catalog-md` after touching rules."""
    from apex_tpu.analysis import catalog_markdown
    readme = open(os.path.join(REPO, "README.md"), encoding="utf-8").read()
    start = readme.index("<!-- apexlint-catalog:start -->")
    end = readme.index("<!-- apexlint-catalog:end -->")
    block = readme[start:end].split("-->", 1)[1].strip("\n")
    assert block == catalog_markdown().strip("\n")


# -- --changed-only ---------------------------------------------------------

def test_changed_only_lints_just_the_diff_set(tmp_path, capsys):
    git = lambda *a: subprocess.run(
        ["git", "-C", str(tmp_path), *a], check=True, capture_output=True,
        env={**os.environ, "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
             "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"})
    git("init", "-q")
    (tmp_path / "pyproject.toml").write_text("[tool.apexlint]\n"
                                             "paths = [\".\"]\n")
    _write(tmp_path, "committed.py", """
        import jax
        def f(key):
            a = jax.random.normal(key, (2,))
            b = jax.random.normal(key, (2,))
            return a + b
        """)
    git("add", "-A")
    git("commit", "-qm", "seed")
    _write(tmp_path, "fresh.py", "x = 1\n")
    old = os.getcwd()
    os.chdir(tmp_path)
    try:
        # committed.py's J004 is invisible: only fresh.py is linted
        assert main(["--no-baseline", "--changed-only"]) == 0
        assert main(["--no-baseline"]) == 1
    finally:
        os.chdir(old)
    capsys.readouterr()
