"""Headline benchmark: learner throughput on one TPU chip + end-to-end rates.

Reference baseline: 10-12 batches/s at batch 512 on a V100 learner fed by a
separate replay server (``origin_repo/README.md:42``; BASELINE.md).  Part 1
measures the SAME unit of work, harder: each learner step here also ingests
512 fresh transitions and performs the PER priority write-back on-device —
work the reference offloads to its replay server — fused into one XLA
program on the Atari-shape DuelingDQN (84x84x4 uint8 stacks, batch 512),
repeated ``REPS`` times for a spread.

Part 2 runs the REAL concurrent pipeline (ApexTrainer + vectorized actor
processes over the shm data plane) on the PIXEL env ``ApexCatch-v0``
(84x84x4 uint8, the flagship geometry — the numpy renderer stands in for
ALE, absent in this image) to measure env-frames/sec ingested and
learner-steps/sec sustained end to end, queue/staging/publish overhead
included.

Replay is the frame-pool layout: 2^19 transitions + 2^20 single frames
resident in HBM (~7.5GB/chip); an 8-chip slice with per-chip shards doubles
the reference's 2e6 total capacity.  Stacks are gathered on device at
sample time.

Part 1 measures two dispatch shapes: one fused step per host round-trip
("single") and a ``lax.scan`` of BENCH_SCAN=8 bit-identical steps per
round-trip ("scanK" — host dispatch is the dominant per-step overhead on
relay-backed chips); the headline takes the faster, with both recorded.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} plus
"spread" (min/max over reps), "mfu", "gather" (the row-gather path actually
used), "dispatch" ("single" | "scanK"), "platform", and "e2e" (the
ApexTrainer rates).
vs_baseline = value / 11.0 (midpoint of the reference's 10-12 range).

Hang hardening (round 3 lost its only on-chip number to a silent 25-minute
stall, rc=124, no JSON; round 4's first live run then lost both parts to a
pallas probe that wedged the DEVICE): the TPU is reached through a relay
that can dial slowly or never, so

* backend init is probed in a SUBPROCESS with a hard timeout first — if the
  platform never comes up, the main process optionally falls back to CPU
  (``platform`` field records which; ``BENCH_CPU_FALLBACK=0`` disables);
* a watchdog thread arms a deadline per stage and, when one is missed,
  prints the accumulated partial result as the final JSON line and exits 0
  — a part-2 hang can no longer lose part 1;
* parts 1 and 2 run on the guaranteed-safe XLA gather FIRST; the pallas
  kernel is attempted LAST (in-process — the relay chip is single-client,
  so a subprocess could not attach — probe, then a part-1 rerun taken as
  a strict upgrade) because a wedged on-device kernel outlives its
  process and blocks every subsequent client.  A hang in this final stage
  trips the watchdog, which emits all the already-recorded numbers and
  exits 0; failures land in ``pallas_error``; ``BENCH_SKIP_PALLAS=1``
  skips the attempt entirely.
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import threading
import time

BASELINE_BPS = 11.0
BATCH = int(os.environ.get("BENCH_BATCH", 512))
FRAME_SHAPE = (84, 84, 1)
FRAME_STACK = 4
CAPACITY = int(os.environ.get("BENCH_CAPACITY", 2 ** 19))
FRAME_CAPACITY = 2 * CAPACITY
CHUNK = BATCH          # transitions ingested per fused step
CHUNK_FRAMES = CHUNK + 16
WARMUP_STEPS = 3
# env overrides let CI smoke-test the bench on CPU at toy scale; the
# driver's real-chip run uses the defaults
MEASURE_STEPS = int(os.environ.get("BENCH_STEPS", 50))
REPS = int(os.environ.get("BENCH_REPS", 3))
# first TPU compile of the concurrent pipeline eats ~20-40s of this wall
# budget and the 2048-transition warmup a further slice; the steady-state
# window after both is what the sliding rate counters report.  On TPU the
# e2e stage is a SOAK: >=300s wall so that >=180s of post-compile steady
# state is measured (round numbers must not be a 37-step sliver); the CPU
# diagnostic lane keeps the short default.
def _e2e_seconds(platform: str) -> float:
    if "BENCH_E2E_SECONDS" in os.environ:
        return float(os.environ["BENCH_E2E_SECONDS"])
    return 300.0 if platform == "tpu" else 120.0


# stage deadlines (watchdog): generous but finite — the whole bench must
# land inside the driver's outer timeout with the JSON line printed
INIT_TIMEOUT = float(os.environ.get("BENCH_INIT_TIMEOUT", 240.0))
PART1_TIMEOUT = float(os.environ.get("BENCH_PART1_TIMEOUT", 360.0))
PART2_MARGIN = float(os.environ.get("BENCH_PART2_MARGIN", 240.0))
PIPELINE_TIMEOUT = float(os.environ.get("BENCH_PIPELINE_TIMEOUT", 300.0))
# wall seconds granted to train() ON TOP of the soak target so the first
# compile of the concurrent pipeline (~20-40s on TPU) cannot eat the
# steady-state window (VERDICT r5 weak #8: the soak used to run INSIDE
# its own budget, leaving no compile margin)
E2E_COMPILE_MARGIN = float(os.environ.get("BENCH_E2E_COMPILE_MARGIN", 90.0))


def e2e_budgets(platform: str) -> tuple[float, float, float]:
    """(soak, train_seconds, stage_seconds) for the e2e stage.

    The soak (:func:`_e2e_seconds`) is the STEADY-STATE wall target; the
    ``train()`` call gets ``soak + E2E_COMPILE_MARGIN`` so compile time
    comes out of the margin, not the soak; and the watchdog stage budget
    adds ``PART2_MARGIN`` on top for trainer construction, actor spawn,
    and teardown.  Unit-tested in tests/test_bench.py — the invariant is
    strict containment: soak < train < stage."""
    soak = _e2e_seconds(platform)
    train_seconds = soak + E2E_COMPILE_MARGIN
    return soak, train_seconds, train_seconds + PART2_MARGIN


# Relay env as the operator launched us (captured BEFORE any CPU
# fallback overwrites it): the late re-probe must dial the ORIGINAL
# backend, not the fallback's cpu pin.
_RELAY_ENV_KEYS = ("JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS")
_ORIG_RELAY_ENV = {k: os.environ.get(k) for k in _RELAY_ENV_KEYS}

# -- watchdog ---------------------------------------------------------------

RESULT: dict = {
    "metric": f"learner_batches_per_sec_batch{BATCH}_framepool_per_ingest",
    "value": None, "unit": "batches/s", "vs_baseline": None,
}
_stage = {"name": "start", "deadline": None}
_done = threading.Event()
_print_lock = threading.Lock()


def _emit_and_exit() -> None:
    # _print_lock also guards RESULT mutations (main thread), so the dump
    # cannot race a concurrent insert; the dict(...) copy is belt-and-braces
    with _print_lock:
        print(json.dumps(dict(RESULT)), flush=True)
    os._exit(0)          # watchdog path: threads/children may be wedged


def _arm(name: str, seconds: float) -> None:
    _stage["name"] = name
    _stage["deadline"] = time.monotonic() + seconds
    print(f"[bench] stage {name} (budget {seconds:.0f}s)",
          file=sys.stderr, flush=True)


def _watchdog() -> None:
    while not _done.wait(2.0):
        dl = _stage["deadline"]
        if dl is not None and time.monotonic() > dl:
            RESULT["error"] = (f"watchdog: stage {_stage['name']!r} "
                               f"exceeded its budget")
            _emit_and_exit()


# -- stage 0: backend probe -------------------------------------------------

def _apply_platform() -> None:
    """Make an explicit ``JAX_PLATFORMS`` stick in the CURRENT process:
    the axon plugin registers at interpreter start (sitecustomize) and
    ignores the env var, so it must be applied via jax.config — the env
    var alone would leave CI's cpu choice spinning on a dead relay.  Safe
    only before the backend is first initialized (true for every caller:
    the main process has not touched jax yet)."""
    p = os.environ.get("JAX_PLATFORMS")
    if p:
        import jax
        jax.config.update("jax_platforms", p)


# the same trick, inlined into the probe subprocess's -c code
_APPLY_PLATFORM_CODE = (
    "import os, jax; p = os.environ.get('JAX_PLATFORMS'); "
    "p and jax.config.update('jax_platforms', p); ")


def _probe_in_subprocess(env: dict | None = None,
                         timeout: float | None = None):
    """One killable backend-init probe.  Returns ``(platform, diag)`` —
    platform None when the init timed out or printed nothing, with the
    tail of its output (or the timeout notice) as ``diag``."""
    code = (_APPLY_PLATFORM_CODE +
            "import jax.numpy as jnp; "
            "d = jax.devices(); "
            "(jnp.ones((256, 256), jnp.bfloat16) @ "
            "jnp.ones((256, 256), jnp.bfloat16)).block_until_ready(); "
            "print('PLATFORM=' + d[0].platform)")
    timeout = INIT_TIMEOUT if timeout is None else timeout
    try:
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout, env=env)
        for line in p.stdout.splitlines():
            if line.startswith("PLATFORM="):
                return line.split("=", 1)[1], None
        return None, (p.stderr or p.stdout or "")[-400:]
    except subprocess.TimeoutExpired:
        return None, f"backend init exceeded {timeout}s"


def probe_backend() -> str:
    """Bring the backend up in a SUBPROCESS first: a dead relay makes
    ``jax.devices()`` spin forever, and a subprocess can be killed where
    the main process cannot un-hang itself.  Returns the platform the main
    process should use ("tpu"/"cpu"/...)."""
    platform, diag = _probe_in_subprocess()
    if platform is not None:
        _apply_platform()       # mirror the choice the probe made
        return platform
    with _print_lock:
        RESULT["backend_probe"] = diag
    if os.environ.get("BENCH_CPU_FALLBACK", "1") != "0":
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        _apply_platform()
        return "cpu"
    RESULT["error"] = RESULT.get("backend_probe", "backend unavailable")
    _emit_and_exit()
    raise AssertionError  # unreachable


def _relay_child_env(environ) -> dict:
    """The current env with the ORIGINAL relay keys restored — what a
    late probe must dial (the CPU fallback pinned cpu into os.environ)."""
    env = dict(environ)
    for k, v in _ORIG_RELAY_ENV.items():
        if v is None:
            env.pop(k, None)
        else:
            env[k] = v
    return env


def _reexec_bench() -> None:
    """Restart the bench in a FRESH process on the original relay env: a
    CPU-initialized jax runtime cannot be re-pointed at the TPU in place.
    ``BENCH_NO_REPROBE`` caps the whole dance at one retry."""
    for k, v in _ORIG_RELAY_ENV.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    os.environ["BENCH_NO_REPROBE"] = "1"
    sys.stdout.flush()
    sys.stderr.flush()
    os.execv(sys.executable, [sys.executable] + sys.argv)


def maybe_reprobe(platform: str, environ=None, probe=None, reexec=None,
                  result: dict | None = None) -> bool:
    """Late backend re-probe (VERDICT r5 weak #1 / next-round item 2).

    Rounds 4 and 5 lost their only TPU shot to a single 240s probe at
    t=0; a relay that warms up DURING the bench still yielded a full-CPU
    round.  Between part 1 and the e2e stage, when (and only when) the
    initial probe FELL BACK — never when the operator explicitly chose a
    platform — re-probe once on the original relay env with the same hard
    subprocess timeout.  If the TPU answers, re-exec the bench so a fresh
    process runs every stage on silicon (strictly better than the CPU
    numbers it discards); otherwise record the attempt and continue.

    ``probe``/``reexec``/``environ``/``result`` are test seams
    (tests/test_bench.py fakes the probe both ways).  Returns True when a
    re-exec was requested."""
    environ = os.environ if environ is None else environ
    result = RESULT if result is None else result
    if platform == "tpu" or environ.get("BENCH_NO_REPROBE") == "1":
        return False
    if "backend_probe" not in result:
        return False            # no fallback happened: cpu was the ask
    if probe is None:
        def probe():
            return _probe_in_subprocess(_relay_child_env(environ))[0]
    got = probe()
    result["late_reprobe"] = got or "no-answer"
    if got != "tpu":
        return False
    (reexec or _reexec_bench)()
    return True


# -- final stage: pallas kernel probe ---------------------------------------

PALLAS_PROBE_TIMEOUT = float(os.environ.get("BENCH_PALLAS_TIMEOUT", 150.0))


def probe_pallas() -> str | None:
    """Compile + run the standalone gather kernel on the real chip.

    Runs IN-PROCESS (the relay chip is single-client, so a subprocess
    could never attach while the bench still holds the backend) and LAST
    (the round-4 live run showed a misbehaving kernel doesn't just fail —
    it can wedge the device for every later client).  By this point every
    safe number is already in RESULT, so a hang here is caught by the
    watchdog, which emits the accumulated JSON and exits 0: the hang
    costs only the pallas upgrade.  Failures land in ``pallas_error``
    rather than silently falling back (VERDICT r3 weak #1)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.gather import ROW_UNIT, _pallas_gather

    try:
        f = 64
        f3 = (jnp.arange(f * ROW_UNIT, dtype=jnp.int32) % 251
              ).astype(jnp.uint8).reshape(f, 8, ROW_UNIT // 8)
        ids = jnp.array([3, 1, 63, 0, 17, 3, 62, 9], jnp.int32)
        out = jax.block_until_ready(_pallas_gather(f3, ids))
        ref = jnp.take(f3.reshape(f, -1), ids, axis=0)
        if not bool(jnp.array_equal(out, ref)):
            raise RuntimeError("on-chip pallas gather != XLA gather")
        return None
    except Exception as exc:
        os.environ["APEX_GATHER_MODE"] = "xla"
        return f"{type(exc).__name__}: {exc}"[:400]


# -- part 1: fused learner step --------------------------------------------

def _synthetic_chunk(rng):
    """A representative actor chunk: CHUNK transitions over CHUNK_FRAMES
    contiguous frames, stacks referencing chunk-relative windows."""
    import numpy as np
    d = int(np.prod(FRAME_SHAPE))
    base = np.minimum(np.arange(CHUNK), CHUNK_FRAMES - 1 - 3)
    offs = np.arange(-(FRAME_STACK - 1), 1)
    obs_ref = np.maximum(base[:, None] + offs[None, :], 0).astype(np.int32)
    next_ref = np.minimum(obs_ref + 3, CHUNK_FRAMES - 1).astype(np.int32)
    chunk = dict(
        frames=rng.integers(0, 255, (CHUNK_FRAMES, d)).astype(np.uint8),
        n_frames=np.int32(CHUNK_FRAMES),
        n_trans=np.int32(CHUNK),
        action=rng.integers(0, 6, CHUNK).astype(np.int32),
        reward=rng.normal(size=CHUNK).astype(np.float32),
        discount=np.full(CHUNK, 0.99 ** 3, np.float32),
        obs_ref=obs_ref,
        next_ref=next_ref,
    )
    prios = np.abs(rng.normal(size=CHUNK)).astype(np.float32) + 1e-3
    return chunk, prios


def bench_fused_step() -> dict:
    """The fused ingest+sample+update+write-back step, pre-staged device
    inputs, REPS timed repetitions."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.models.dueling import DuelingDQN
    from apex_tpu.ops.gather import resolved_mode
    from apex_tpu.ops.losses import make_optimizer
    from apex_tpu.replay.frame_pool import FramePoolReplay
    from apex_tpu.training.learner import LearnerCore
    from apex_tpu.training.state import create_train_state

    model = DuelingDQN(num_actions=6)
    pool = FramePoolReplay(capacity=CAPACITY, frame_shape=FRAME_SHAPE,
                           frame_stack=FRAME_STACK,
                           frame_capacity=FRAME_CAPACITY)
    optimizer = make_optimizer()
    ts = create_train_state(
        model, optimizer, jax.random.key(0),
        jnp.zeros((1, 84, 84, FRAME_STACK), jnp.uint8))
    core = LearnerCore(apply_fn=model.apply, replay=pool,
                       optimizer=optimizer, batch_size=BATCH,
                       target_update_interval=2500)
    rs = pool.init()
    gather = resolved_mode(rs.frames, pool.gather_mode)

    rng = np.random.default_rng(0)
    chunk, prios = _synthetic_chunk(rng)
    chunk = jax.device_put(chunk)
    prios = jax.device_put(jnp.asarray(prios))

    fused = core.jit_fused_step()
    for i in range(WARMUP_STEPS):
        ts, rs, metrics = fused(ts, rs, chunk, prios, jax.random.key(i),
                                jnp.float32(0.4))
    jax.block_until_ready(metrics["loss"])

    rates = []
    for rep in range(REPS):
        t0 = time.perf_counter()
        for i in range(MEASURE_STEPS):
            ts, rs, metrics = fused(ts, rs, chunk, prios,
                                    jax.random.key(1000 * rep + i),
                                    jnp.float32(0.4))
        jax.block_until_ready(metrics["loss"])
        rates.append(MEASURE_STEPS / (time.perf_counter() - t0))

    from apex_tpu.utils.profiling import DEFAULT_PEAK, flops_per_call, mfu
    flops = flops_per_call(fused, ts, rs, chunk, prios, jax.random.key(0),
                           jnp.float32(0.4))
    peak = (float(os.environ["BENCH_PEAK_TFLOPS"]) * 1e12
            if "BENCH_PEAK_TFLOPS" in os.environ else DEFAULT_PEAK)
    util = mfu(flops, float(np.median(rates)), peak)
    out = {"median": float(np.median(rates)),
           "min": round(min(rates), 2), "max": round(max(rates), 2),
           "reps": REPS, "gather": gather,
           "mfu": None if util is None else round(util, 4)}

    # scan-of-K dispatch: same per-step program (tests pin bit-parity),
    # K fewer host round-trips — the dominant overhead on relay-backed
    # chips.  Reported per-STEP so the unit stays comparable.  main()
    # zeroes BENCH_SCAN on non-TPU platforms: XLA:CPU lowers the conv
    # backward ~20x slower inside while-loops (measured), so a CPU scan
    # number is a backend artifact, not a signal.
    k = int(os.environ.get("BENCH_SCAN", 8))
    if k > 1:
        multi = core.jit_fused_multi_step()
        stacked = jax.device_put(jax.tree.map(
            lambda x: jnp.stack([jnp.asarray(x)] * k), chunk))
        sprios = jax.device_put(jnp.stack([jnp.asarray(prios)] * k))
        n_dispatch = max(1, MEASURE_STEPS // k)
        keys = jax.random.split(jax.random.key(7), k)
        ts, rs, m = multi(ts, rs, stacked, sprios, keys, jnp.float32(0.4))
        jax.block_until_ready(m["loss"])              # compile + warm
        scan_rates = []
        for rep in range(REPS):
            t0 = time.perf_counter()
            for i in range(n_dispatch):
                keys = jax.random.split(
                    jax.random.key(5000 + 1000 * rep + i), k)
                ts, rs, m = multi(ts, rs, stacked, sprios, keys,
                                  jnp.float32(0.4))
            jax.block_until_ready(m["loss"])
            scan_rates.append(n_dispatch * k
                              / (time.perf_counter() - t0))
        # apexlint: disable=J004 -- flops probe re-invokes with measurement-only keys
        sflops = flops_per_call(multi, ts, rs, stacked, sprios, keys,
                                jnp.float32(0.4))
        sutil = mfu(None if sflops is None else sflops / k,
                    float(np.median(scan_rates)), peak)
        out["scan"] = {"k": k, "median": float(np.median(scan_rates)),
                       "min": round(min(scan_rates), 2),
                       "max": round(max(scan_rates), 2),
                       "mfu": None if sutil is None else round(sutil, 4)}
    return out


# -- part 1b: async ingest pipeline on vs off -------------------------------

def bench_ingest_pipeline(n_dp: int = 1) -> dict:
    """The per-ingest framepool hot loop through the REAL concurrent
    trainer, pipeline ON vs OFF, same pre-recorded chunk stream.

    ``n_dp > 1`` runs the SAME A/B over the sharded (shard_map) plan:
    chunks round-robin onto ``n_dp`` replay shards through the
    ChunkAggregator, the pipelined lane stages whole groups (per-shard
    merged when ingest-only) plus pre-split per-chip keys, and the
    serial lane pays the per-dispatch split_ingest/device_keys cost
    inline — the exact contrast the dp staging follow-up exists to
    measure.  Runs in the dp child process (``--dp-pipe-child``) on the
    host-platform-device-count emulated mesh.

    The stream arrives PICKLED (the decode cost every real data plane
    pays — mp.Queue pickle or socket recv) through an in-process pool, in
    the ingest-dominant regime a production Ape-X learner actually runs
    (train_ratio caps steps well below chunk supply, so most chunks are
    absorbed ingest-only).  Serial pays decode + H2D + one dispatch per
    chunk inline on the hot loop; the pipeline moves decode/staging onto
    the background thread and coalesces ingest-only chunks into merged
    payloads (training/ingest_pipeline.py).  Both lanes run the same
    step/transition quantum, so the transitions-per-second ratio is the
    pipeline's honest speedup on this machine — recorded either way,
    with the dispatch-gap stats that locate where the host time went.

    Small MLP geometry on purpose: the stage measures the INGEST path
    (dispatch count, decode, staging), not MXU throughput — part 1 and
    the e2e stage own those.
    """
    import pickle

    import numpy as np

    from apex_tpu.config import (ActorConfig, ApexConfig, EnvConfig,
                                 LearnerConfig, ReplayConfig)
    from apex_tpu.replay.frame_chunks import FrameChunkBuilder
    from apex_tpu.runtime import codec as wire_codec
    from apex_tpu.training.apex import ApexTrainer

    # the chunk stream honors APEX_WIRE_CODEC (default raw): under
    # delta/dict the A/B re-runs with every poll paying the codec's
    # decode instead of a plain unpickle — the ingest-envelope check the
    # part-1g acceptance bar asks of the compressed lanes
    bench_codec = wire_codec.resolve_codec(None)
    chunk_k = int(os.environ.get("BENCH_PIPE_CHUNK", 128))
    batch = int(os.environ.get("BENCH_PIPE_BATCH", 128))
    ratio = float(os.environ.get("BENCH_PIPE_RATIO", 0.015625))
    steps = int(os.environ.get("BENCH_PIPE_STEPS", 24))
    reps = int(os.environ.get("BENCH_PIPE_REPS", 2))
    warm_steps = 4
    # chunk supply sized so neither lane ever runs dry: warmup fill plus
    # steps/ratio budget over all reps, with 2x headroom.  A small set of
    # UNIQUE chunks is recycled to keep stream generation off the stage
    # budget — every poll still pays the full decode (fresh pickle.loads
    # per message), which is what the lanes measure.
    n_chunks = int(2 * (1024 + (warm_steps + reps * steps) * batch / ratio)
                   / chunk_k) + 8
    n_unique = min(n_chunks, 96)

    rng = np.random.default_rng(0)
    builder = FrameChunkBuilder(3, 0.99, 1, (4,), chunk_transitions=chunk_k,
                                frame_dtype=np.float32)
    unique: list[bytes] = []
    while len(unique) < n_unique:
        builder.begin_episode(rng.normal(size=4).astype(np.float32))
        ep_len = int(rng.integers(20, 200))
        for t in range(ep_len):
            builder.add_step(int(rng.integers(0, 2)), float(rng.normal()),
                             rng.normal(size=2).astype(np.float32),
                             rng.normal(size=4).astype(np.float32),
                             terminated=t == ep_len - 1, truncated=False)
        for chunk in builder.poll():
            prios = chunk.pop("priorities")
            unique.append(wire_codec.encode_chunk(
                {"payload": chunk, "priorities": prios,
                 "n_trans": int(chunk["n_trans"])}, bench_codec)[0])
    unique = unique[:n_unique]
    blobs = [unique[i % n_unique] for i in range(n_chunks)]

    def _load(b: bytes) -> dict:
        # in-process replay of a stream this bench encoded itself; no
        # trust boundary
        # apexlint: disable=C005 -- same-process bench stream
        kind, body = pickle.loads(b)
        return (wire_codec.decode_chunk(body) if kind == "chunkc"
                else body)

    class _PickledStreamPool:
        """In-process stand-in for the worker data plane: chunks decode
        (unpickle) at poll time — on the hot loop serially, on the
        staging thread pipelined; params pay the publish serialization
        either way."""

        def __init__(self, stream):
            self._stream = list(stream)
            self.procs = []

        def start(self):
            pass

        def cleanup(self):
            pass

        def publish_params(self, version, params):
            pickle.dumps(params, protocol=pickle.HIGHEST_PROTOCOL)

        def poll_stats(self):
            return []

        def poll_chunks(self, max_chunks, timeout=0.0):
            out = []
            while self._stream and len(out) < max_chunks:
                out.append(_load(self._stream.pop(0)))
            return out

    def warm_shapes(trainer, pipeline_on: bool) -> None:
        """Compile every dispatch shape the lane will use OUTSIDE the
        timed window, on throwaway copies of the donated states (the
        compile cost is a once-per-process constant, not the per-step
        throughput this stage measures)."""
        import jax
        import jax.numpy as jnp

        from apex_tpu.training.ingest_pipeline import (merge_chunk_messages,
                                                       merge_group_messages)

        def cp(tree):
            return jax.tree.map(jnp.copy, tree)

        key_f, key_t = jax.random.split(jax.random.key(999))
        beta = jnp.float32(0.4)
        merge_max = trainer.cfg.learner.pipeline_merge
        msgs = [_load(b) for b in blobs[:merge_max * max(1, n_dp)]]
        if n_dp > 1:
            # the dp lanes dispatch GROUP-granular payloads (aggregator
            # stacking); merged widths per-shard-merge whole groups
            from apex_tpu.parallel.aggregate import stack_chunk_messages
            groups = []
            for i in range(0, len(msgs) - n_dp + 1, n_dp):
                payload, gprios, n_tr = stack_chunk_messages(
                    msgs[i:i + n_dp])
                groups.append({"payload": payload, "priorities": gprios,
                               "n_trans": n_tr})
            msgs = groups
            merge = lambda mm: merge_group_messages(mm, n_dp)  # noqa: E731
        else:
            merge = merge_chunk_messages

        def forms(msg):
            payload = msg["payload"]
            prios = np.asarray(msg["priorities"], np.float32)
            if pipeline_on and n_dp == 1:  # staged slots: device arrays
                return jax.device_put(payload), jax.device_put(prios)
            return payload, jnp.asarray(prios)

        pay, pr = forms(msgs[0])
        jax.block_until_ready(
            trainer._ingest(cp(trainer.replay_state), pay, pr))
        out = trainer._fused(cp(trainer.train_state),
                             cp(trainer.replay_state), pay, pr, key_f, beta)
        jax.block_until_ready(out[2]["loss"])
        out = trainer._train(cp(trainer.train_state),
                             cp(trainer.replay_state), key_t, beta)
        jax.block_until_ready(out[2]["loss"])
        if pipeline_on:
            w, outs = 2, []
            while w <= merge_max and w <= len(msgs):
                mpay, mpr = forms(merge(msgs[:w]))
                outs.append(trainer._ingest(cp(trainer.replay_state),
                                            mpay, mpr))
                w *= 2
            jax.block_until_ready(outs)

    def lane(pipeline_on: bool) -> dict:
        cfg = ApexConfig(
            env=EnvConfig(env_id="ApexCartPole-v0", frame_stack=1,
                          clip_rewards=False, episodic_life=False),
            replay=ReplayConfig(capacity=2 ** 13, warmup=1024),
            learner=LearnerConfig(batch_size=batch, ingest_chunk=chunk_k,
                                  compute_dtype="float32",
                                  target_update_interval=500,
                                  ingest_pipeline=pipeline_on,
                                  pipeline_merge=32,
                                  mesh_shape=(n_dp,)),
            actor=ActorConfig(n_actors=1, send_interval=chunk_k),
        )
        trainer = ApexTrainer(cfg, pool=_PickledStreamPool(blobs),
                              publish_min_seconds=1.0, train_ratio=ratio,
                              respawn_workers=False)
        warm_shapes(trainer, pipeline_on)
        # warm call: the loop's own paths (publish copies, rate counters)
        trainer.train(total_steps=warm_steps, max_seconds=120,
                      log_every=10 ** 9)
        runs = []
        for _ in range(reps):        # best-of-reps damps 1-core scheduler
            ingested0 = trainer.ingested         # noise in short windows
            steps0 = trainer.steps_rate.total
            t0 = time.perf_counter()
            trainer.train(total_steps=steps, max_seconds=120,
                          log_every=10 ** 9)
            dt = time.perf_counter() - t0
            runs.append({
                "trans_per_sec":
                    round((trainer.ingested - ingested0) / dt, 1),
                "steps_per_sec":
                    round((trainer.steps_rate.total - steps0) / dt, 2),
                "seconds": round(dt, 2),
                "transitions": trainer.ingested - ingested0,
                "dispatch_gap": trainer._dispatch_gap.snapshot(),
            })
        out = max(runs, key=lambda r: r["trans_per_sec"])
        out["reps"] = [r["trans_per_sec"] for r in runs]
        if pipeline_on:
            out["pipeline"] = trainer._pipeline_last_stats
        return out

    serial = lane(False)
    pipelined = lane(True)
    speedup = (pipelined["trans_per_sec"] / serial["trans_per_sec"]
               if serial["trans_per_sec"] else None)
    return {"geometry": f"cartpole-mlp_b{batch}_k{chunk_k}"
                        + (f"_dp{n_dp}" if n_dp > 1 else ""),
            "n_dp": n_dp, "wire_codec": bench_codec,
            "train_ratio": ratio, "steps": steps,
            "serial": serial, "pipelined": pipelined,
            "speedup": None if speedup is None else round(speedup, 3)}


# -- part 1c: the dp>1 lane in a device-count-emulated child ----------------

DP_PIPE_DEVICES = int(os.environ.get("BENCH_DP_PIPE_DEVICES", 4))
DP_PIPE_TIMEOUT = float(os.environ.get("BENCH_DP_PIPE_TIMEOUT", 420.0))


def _dp_pipe_child() -> None:
    """Child entry (``bench.py --dp-pipe-child``): run the part-1b A/B
    over the sharded plan and print ONE JSON line.  The parent launched
    us with JAX_PLATFORMS=cpu and
    ``--xla_force_host_platform_device_count=DP_PIPE_DEVICES`` — device
    count is a process-startup flag, so the dp mesh can only exist in a
    fresh interpreter (the parent's backend is already initialized).

    Default chunk size is SMALLER than the single-shard lane's: a
    round-robin group is ``n_dp`` chunks, so equal-size chunks would
    start the serial dp lane with its dispatch overhead already
    amortized n_dp-fold and the A/B would measure mostly the merge copy
    cost.  chunk 32 x dp 4 keeps the per-dispatch transition quantum
    (128) equal to the single-shard lane's — the same
    dispatch-overhead-dominant regime, now over the shard_map plan."""
    _apply_platform()
    os.environ.setdefault("BENCH_PIPE_CHUNK",
                          os.environ.get("BENCH_DP_PIPE_CHUNK", "32"))
    try:
        out = bench_ingest_pipeline(n_dp=DP_PIPE_DEVICES)
    except Exception as exc:
        out = {"error": f"{type(exc).__name__}: {exc}"[:400]}
    print(json.dumps(out), flush=True)


def bench_ingest_pipeline_dp() -> dict:
    """Spawn the dp>1 pipeline A/B on a CPU mesh emulated via
    ``--xla_force_host_platform_device_count`` in a subprocess, and
    relay its JSON (with per-lane DispatchGapTimer stats, so the
    multichip artifacts pick up the sharded loop's gap trend)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count"
            f"={DP_PIPE_DEVICES}").strip()
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--dp-pipe-child"],
            capture_output=True, text=True, timeout=DP_PIPE_TIMEOUT,
            env=env)
    except subprocess.TimeoutExpired:
        return {"error": f"dp child exceeded {DP_PIPE_TIMEOUT}s"}
    for line in reversed(p.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    return {"error": (p.stderr or p.stdout or "dp child: no output")[-400:]}


# -- part 1d: actor-plane double-buffer A/B ---------------------------------

ACTOR_AB_TIMEOUT = float(os.environ.get("BENCH_ACTOR_AB_TIMEOUT", 300.0))


def _burn_cpu(n: int = 4_000_000) -> int:
    """Fixed CPU burn for the effective-core probe (module-level: spawn
    contexts pickle the target by reference)."""
    x = 0
    for i in range(n):
        x += i * i
    return x


def _burn_child(n: int, barrier, out_q) -> None:
    """Probe child: sync on the barrier (so both children burn
    CONCURRENTLY and spawn startup stays out of the measurement), then
    time its own burn."""
    barrier.wait()
    t0 = time.perf_counter()
    _burn_cpu(n)
    out_q.put(time.perf_counter() - t0)


def _effective_cores(samples: int = 2) -> float:
    """Measured parallel CPU capacity (2-process scaling of a fixed burn,
    barrier-synced, per-child timed).  The double-buffer A/B is a PURE
    SCHEDULING experiment (both modes run bit-identical work — the parity
    pin demands it), so its ceiling is exactly this number: a 1-core
    cgroup shows ~1.0x by physics, a 2-core actor host can show the real
    overlap win.  Recorded so the artifact is interpretable across
    boxes."""
    import multiprocessing as mp
    import queue as queue_lib

    n = 4_000_000
    ctx = mp.get_context("spawn")
    ones = []
    for _ in range(samples):
        t0 = time.perf_counter()
        _burn_cpu(n)
        ones.append(time.perf_counter() - t0)
    ratios = []
    for _ in range(samples):
        barrier = ctx.Barrier(3)
        out_q = ctx.Queue()
        ps = [ctx.Process(target=_burn_child, args=(n, barrier, out_q),
                          daemon=True) for _ in range(2)]
        try:
            for p in ps:
                p.start()
            # a child that dies before the barrier (spawn pickling only
            # resolves _burn_child when this module is importable under
            # its real name) must never hang the probe: bounded waits,
            # 0.0 = probe unavailable
            barrier.wait(timeout=30)
            times = [out_q.get(timeout=60) for _ in range(2)]
        except (threading.BrokenBarrierError, queue_lib.Empty):
            return 0.0
        finally:
            for p in ps:
                if p.is_alive():
                    p.terminate()
                p.join(timeout=10)
        ratios.append(2 * min(ones) / max(max(times), 1e-9))
    return round(max(ratios), 2)


def bench_actor_plane() -> dict:
    """Part 1d: the vector-actor hot loop, double-buffer on vs off, same
    fixed-seed env batch and key chain (the modes are bit-identical per
    slot — tests/test_vector.py pins it — so frames/s is the ONLY thing
    the knob changes).  Two geometries: the toy CartPole MLP (dispatch-
    overhead regime) and the 84x84x4 pixel conv (inference-bound regime,
    the flagship shape).  Reports per-mode frames/s and the PhaseTimer
    overlap split (policy-wait / env-step fractions), plus the box's
    measured effective cores — the scheduling win's hard ceiling."""
    import jax
    import numpy as np

    from apex_tpu.actors.pool import actor_epsilons
    from apex_tpu.actors.vector import VectorDQNWorkerFamily
    from apex_tpu.config import ApexConfig, ActorConfig, EnvConfig
    from apex_tpu.models.dueling import DuelingDQN
    from apex_tpu.ops.losses import make_optimizer
    from apex_tpu.training.apex import dqn_env_specs
    from apex_tpu.training.state import create_train_state

    steps = int(os.environ.get("BENCH_ACTOR_STEPS", 60))
    reps = int(os.environ.get("BENCH_ACTOR_REPS", 3))
    warm = 6

    def make_family(env_cfg: EnvConfig, n_envs: int, double_buffer: bool):
        cfg = ApexConfig(env=env_cfg,
                         actor=ActorConfig(n_actors=1,
                                           n_envs_per_actor=n_envs,
                                           double_buffer=double_buffer))
        model_spec, frame_shape, frame_dtype, frame_stack = \
            dqn_env_specs(cfg)
        fam = VectorDQNWorkerFamily(
            cfg, model_spec,
            seeds=[cfg.env.seed + 1000 * (s + 1) for s in range(n_envs)],
            slot_ids=list(range(n_envs)),
            epsilons=actor_epsilons(n_envs), chunk_transitions=64)
        model = DuelingDQN(**model_spec)
        stacked = frame_shape[:-1] + (frame_stack * frame_shape[-1],)
        ts = create_train_state(model, make_optimizer(), jax.random.key(0),
                                np.zeros((1,) + stacked, frame_dtype))
        fam.reset_all()
        return fam, ts.params

    def timed_window(fam, params, key, n_steps: int):
        fam.phase.window(reset=True)
        t0 = time.perf_counter()
        for _ in range(n_steps):
            key, k = jax.random.split(key)
            fam.step_all(params, k)
            fam.poll_msgs()
        dt = time.perf_counter() - t0
        w = fam.phase.window(reset=False)
        return key, {
            "frames_per_sec": round(n_steps * fam.n_envs / dt, 1),
            "policy_wait_frac":
                round(w["fracs"].get("policy_wait", 0.0), 3),
            "env_step_frac": round(w["fracs"].get("env_step", 0.0), 3),
            "dispatch_gap_ms_p50":
                round(fam.gap.snapshot()["dispatch_gap_ms_p50"], 3),
            "seconds": round(dt, 2)}

    def ab(env_cfg: EnvConfig, n_envs: int, n_steps: int) -> dict:
        fams = {mode: make_family(env_cfg, n_envs, mode)
                for mode in (False, True)}
        keys = {mode: jax.random.key(7) for mode in fams}
        for mode, (fam, params) in fams.items():     # compile + warm
            for _ in range(warm):
                keys[mode], k = jax.random.split(keys[mode])
                fam.step_all(params, k)
                fam.poll_msgs()
        runs: dict[bool, list] = {False: [], True: []}
        for _ in range(reps):         # alternate modes so scheduler drift
            for mode in (False, True):     # hits both; best-of-reps damps
                fam, params = fams[mode]   # 1-core noise (cf. part 1b)
                keys[mode], r = timed_window(fam, params, keys[mode],
                                             n_steps)
                runs[mode].append(r)
        best = {mode: max(rs, key=lambda r: r["frames_per_sec"])
                for mode, rs in runs.items()}
        for mode, rs in runs.items():
            best[mode]["reps"] = [r["frames_per_sec"] for r in rs]
        for fam, _ in fams.values():
            fam.close()
        return {
            "n_envs": n_envs, "vector_steps": n_steps,
            "off": best[False], "on": best[True],
            "speedup": (round(best[True]["frames_per_sec"]
                              / best[False]["frames_per_sec"], 3)
                        if best[False]["frames_per_sec"] else None)}

    toy = EnvConfig(env_id="ApexCartPole-v0", frame_stack=1,
                    clip_rewards=False, episodic_life=False)
    pixel = EnvConfig(env_id="ApexCatch-v0", frame_stack=FRAME_STACK,
                      clip_rewards=False, episodic_life=False)
    return {"effective_cores": _effective_cores(),
            "toy": ab(toy, 32, steps * 4),
            "pixel": ab(pixel, 16, steps)}


# -- part 1e: inference-plane remote/local A/B ------------------------------

INFER_AB_TIMEOUT = float(os.environ.get("BENCH_INFER_AB_TIMEOUT", 300.0))


def bench_infer_plane() -> dict:
    """Part 1e: the vector-actor hot loop with the policy served by the
    centralized inference plane vs computed locally, same fixed-seed env
    batch and key chain (remote and local are BIT-IDENTICAL per slot —
    tests/test_infer.py pins it — so frames/s, round-trip, and coalesce
    latency are the ONLY things the knob changes).  The server runs
    in-process on a second thread, which on this 1-core driver box makes
    remote a pure-plumbing-cost measurement; ``effective_cores`` is
    recorded like part 1d so a multi-core/TPU run's real batching win
    stays legible against it."""
    import socket as socket_lib
    import threading as threading_lib

    import jax
    import numpy as np

    from apex_tpu.actors.pool import actor_epsilons
    from apex_tpu.actors.vector import VectorDQNWorkerFamily
    from apex_tpu.config import (ActorConfig, ApexConfig, CommsConfig,
                                 EnvConfig)
    from apex_tpu.infer_service.client import InferClient
    from apex_tpu.infer_service.service import InferServer
    from apex_tpu.models.dueling import DuelingDQN, make_policy_fn
    from apex_tpu.ops.losses import make_optimizer
    from apex_tpu.training.apex import dqn_env_specs
    from apex_tpu.training.state import create_train_state

    steps = int(os.environ.get("BENCH_INFER_STEPS", 120))
    warm = 6

    def free_port() -> int:
        s = socket_lib.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def ab(env_cfg: EnvConfig, n_envs: int, n_steps: int) -> dict:
        comms = CommsConfig(infer_port=free_port())
        cfg = ApexConfig(env=env_cfg, comms=comms,
                         actor=ActorConfig(n_actors=1,
                                           n_envs_per_actor=n_envs))
        model_spec, frame_shape, frame_dtype, frame_stack = \
            dqn_env_specs(cfg)
        model = DuelingDQN(**model_spec)
        stacked = frame_shape[:-1] + (frame_stack * frame_shape[-1],)
        ts = create_train_state(model, make_optimizer(), jax.random.key(0),
                                np.zeros((1,) + stacked, frame_dtype))
        server = InferServer(comms, make_policy_fn(model), heartbeat=False)
        server.set_params(1, ts.params)
        stop = threading_lib.Event()
        thread = threading_lib.Thread(target=server.run,
                                      kwargs={"stop_event": stop},
                                      daemon=True)
        thread.start()

        out: dict = {"n_envs": n_envs, "vector_steps": n_steps}
        try:
            for mode in ("local", "remote"):
                fam = VectorDQNWorkerFamily(
                    cfg, model_spec,
                    seeds=[cfg.env.seed + 1000 * (s + 1)
                           for s in range(n_envs)],
                    slot_ids=list(range(n_envs)),
                    epsilons=actor_epsilons(n_envs), chunk_transitions=64)
                if mode == "remote":
                    fam.attach_infer(InferClient(comms, "bench-actor",
                                                 wait_s=10.0))
                fam.reset_all()
                key = jax.random.key(7)
                for _ in range(warm):
                    key, k = jax.random.split(key)
                    fam.step_all(ts.params, k)
                    fam.poll_msgs()
                t0 = time.perf_counter()
                for _ in range(n_steps):
                    key, k = jax.random.split(key)
                    fam.step_all(ts.params, k)
                    fam.poll_msgs()
                dt = time.perf_counter() - t0
                out[mode] = {
                    "frames_per_sec": round(n_steps * n_envs / dt, 1),
                    "seconds": round(dt, 2)}
                if mode == "remote":
                    client = fam.infer
                    rt = client.round_trip.snapshot()
                    out[mode] |= {
                        "remote_steps": client.remote_steps,
                        "fallbacks": client.fallbacks,
                        "round_trip_ms": {
                            "p50": round(rt["p50_s"] * 1000, 3),
                            "p90": round(rt["p90_s"] * 1000, 3),
                            "p99": round(rt["p99_s"] * 1000, 3)}}
                fam.close()
            b = server.batch_hist.snapshot()
            c = server.coalesce_hist.snapshot()
            out["server"] = {
                "dispatches": server.dispatches,
                "mean_batch": round(b["mean_s"], 2),
                "batch_p90": b["p90_s"],
                "coalesce_ms_p50": round(c["p50_s"] * 1000, 3),
                "coalesce_ms_p90": round(c["p90_s"] * 1000, 3)}
            out["speedup"] = (round(out["remote"]["frames_per_sec"]
                                    / out["local"]["frames_per_sec"], 3)
                              if out["local"]["frames_per_sec"] else None)
        finally:
            stop.set()
            thread.join(timeout=10)
            server.close()
        return out

    toy = EnvConfig(env_id="ApexCartPole-v0", frame_stack=1,
                    clip_rewards=False, episodic_life=False)
    pixel = EnvConfig(env_id="ApexCatch-v0", frame_stack=FRAME_STACK,
                      clip_rewards=False, episodic_life=False)
    return {"effective_cores": _effective_cores(),
            "toy": ab(toy, 32, steps),
            "pixel": ab(pixel, 16, max(10, steps // 4))}


# -- part 1f: on-device Anakin rollout vs host vector-actor ------------------

ONDEVICE_AB_TIMEOUT = float(os.environ.get("BENCH_ONDEVICE_TIMEOUT", 420.0))


def bench_ondevice_rollout() -> dict:
    """Part 1f: the fused on-device rollout engine (training/anakin.py —
    env step + epsilon-greedy policy + chunk assembly in ONE lax.scan) vs
    the host vector-actor loop on the same env/model/ladder.

    The host lane is measured at TWO widths: ``host_default`` is the
    shipping default topology (``n_envs_per_actor=1`` — the reference's
    one-env-per-process shape), whose per-step dispatch + python overhead
    is exactly what the fused scan retires (the 5x-class win on this
    1-core box); ``host_wide`` is width-matched to the engine's B, where
    both lanes are policy-conv-bound on one core and the multiplier
    collapses toward parity — the honest ceiling ``effective_cores``
    contextualizes, and the lane a TPU run blows open (the conv is ~free
    on the MXU while the host lane stays CPU-bound).  ``chunks_per_sec``/
    ``transitions_per_sec`` are the sealed-chunk rate into the replay
    path — the loadgen saturation figure.

    The third lane ``ondevice_fused`` (apex_tpu/ondevice/fused.py) runs
    the WHOLE training cycle — rollout + ingest + prioritized sample +
    train + priority write-back — as one device program per dispatch and
    reports acting throughput (``frames_per_sec``, apples-to-apples with
    the other lanes, which do no training) PLUS ``train_steps_per_sec``,
    the number the host loops pay dispatch round-trips for.  Leaf names
    end in ``per_sec`` so the ``obs.slo --check`` differ classifies the
    lane higher-better automatically."""
    import jax
    import numpy as np

    from apex_tpu.actors.pool import actor_epsilons
    from apex_tpu.actors.vector import VectorDQNWorkerFamily
    from apex_tpu.config import ActorConfig, ApexConfig, EnvConfig
    from apex_tpu.models.dueling import DuelingDQN
    from apex_tpu.ops.losses import make_optimizer
    from apex_tpu.training.anakin import make_anakin_engine
    from apex_tpu.training.apex import dqn_env_specs
    from apex_tpu.training.state import create_train_state

    dispatches = int(os.environ.get("BENCH_ONDEVICE_STEPS", 12))
    rollout_len = int(os.environ.get("BENCH_ONDEVICE_T", 64))

    def ab(env_cfg: EnvConfig, n_envs: int) -> dict:
        cfg = ApexConfig(env=env_cfg,
                         actor=ActorConfig(n_actors=1,
                                           n_envs_per_actor=n_envs,
                                           send_interval=64))
        model_spec, frame_shape, frame_dtype, frame_stack = \
            dqn_env_specs(cfg)
        model = DuelingDQN(**model_spec)
        stacked = frame_shape[:-1] + (frame_stack * frame_shape[-1],)
        ts = create_train_state(model, make_optimizer(),
                                jax.random.key(0),
                                np.zeros((1,) + stacked, frame_dtype))
        params = jax.device_get(ts.params)

        engine = make_anakin_engine(cfg, rollout_len=rollout_len)
        engine.rollout(params)                       # compile + warm
        t0 = time.perf_counter()
        for _ in range(dispatches):
            engine.rollout(params)
        dt = time.perf_counter() - t0
        out = {"n_envs": n_envs, "rollout_len": engine.T,
               "dispatches": dispatches,
               "ondevice": {
                   "frames_per_sec":
                       round(dispatches * engine.T * engine.B / dt, 1),
                   "chunks_per_sec": round(engine.chunks / dt, 2),
                   "transitions_per_sec":
                       round(engine.transitions / dt, 1),
                   "seconds": round(dt, 2)}}

        for label, hb, steps in (("host_default", 1, 300),
                                 ("host_wide", n_envs, 40)):
            hcfg = ApexConfig(env=env_cfg,
                              actor=ActorConfig(n_actors=1,
                                                n_envs_per_actor=hb,
                                                send_interval=64))
            fam = VectorDQNWorkerFamily(
                hcfg, model_spec,
                seeds=[hcfg.env.seed + 1000 * (s + 1) for s in range(hb)],
                slot_ids=list(range(hb)),
                epsilons=actor_epsilons(max(hb, 1)), chunk_transitions=64)
            fam.reset_all()
            key = jax.random.key(7)
            for _ in range(5):
                key, k = jax.random.split(key)
                fam.step_all(params, k)
                fam.poll_msgs()
            t0 = time.perf_counter()
            for _ in range(steps):
                key, k = jax.random.split(key)
                fam.step_all(params, k)
                fam.poll_msgs()
            hdt = time.perf_counter() - t0
            out[label] = {"n_envs": hb,
                          "frames_per_sec": round(steps * hb / hdt, 1),
                          "seconds": round(hdt, 2)}
            fam.close()

        # lane 3: the fused train step — fresh engine/replay/train state
        # so the acting key chains match the ondevice lane's shape
        from apex_tpu.ondevice.fused import FusedStep
        from apex_tpu.replay.frame_pool import FramePoolReplay
        from apex_tpu.training.learner import LearnerCore
        spd = 2
        replay = FramePoolReplay(
            capacity=4096, frame_shape=frame_shape,
            frame_stack=frame_stack,
            frame_dtype=np.dtype(frame_dtype).name)
        fused = FusedStep(
            LearnerCore(apply_fn=model.apply, replay=replay,
                        optimizer=make_optimizer(), batch_size=64,
                        target_update_interval=500),
            replay, make_anakin_engine(cfg, rollout_len=rollout_len),
            warmup=256, beta=0.4, beta_anneal=50_000,
            steps_per_dispatch=spd)
        fts = create_train_state(model, make_optimizer(),
                                 jax.random.key(1),
                                 np.zeros((1,) + stacked, frame_dtype))
        frs, fkey = replay.init(), jax.random.key(3)
        fts, frs, fkey, _ = fused.dispatch(fts, frs, fkey)  # compile+warm
        base_steps, base_trans = fused.train_steps, fused.transitions
        fdisp = max(2, dispatches // 2)
        t0 = time.perf_counter()
        for _ in range(fdisp):
            fts, frs, fkey, _ = fused.dispatch(fts, frs, fkey)
        fdt = time.perf_counter() - t0
        out["ondevice_fused"] = {
            "n_envs": n_envs, "rollout_len": fused.engine.T,
            "steps_per_dispatch": spd, "dispatches": fdisp,
            "frames_per_sec":
                round(fdisp * spd * fused.engine.T * fused.engine.B
                      / fdt, 1),
            "train_steps_per_sec":
                round((fused.train_steps - base_steps) / fdt, 2),
            "transitions_per_sec":
                round((fused.transitions - base_trans) / fdt, 1),
            "seconds": round(fdt, 2)}

        ond = out["ondevice"]["frames_per_sec"]
        out["speedup"] = (round(ond
                                / out["host_default"]["frames_per_sec"],
                                2)
                          if out["host_default"]["frames_per_sec"]
                          else None)
        out["speedup_vs_wide"] = (
            round(ond / out["host_wide"]["frames_per_sec"], 2)
            if out["host_wide"]["frames_per_sec"] else None)
        out["fused_speedup"] = (
            round(out["ondevice_fused"]["frames_per_sec"]
                  / out["host_default"]["frames_per_sec"], 2)
            if out["host_default"]["frames_per_sec"] else None)
        return out

    toy = EnvConfig(env_id="ApexCatchSmall-v0", frame_stack=2,
                    clip_rewards=False, episodic_life=False)
    pixel = EnvConfig(env_id="ApexCatch-v0", frame_stack=FRAME_STACK,
                      clip_rewards=False, episodic_life=False)
    return {"effective_cores": _effective_cores(),
            "toy": ab(toy, 32),
            "pixel": ab(pixel, 16)}


# -- part 1f lane 4: fused macro-step x dp mesh (PR 17) ----------------------

FUSED_DP_DEVICES = int(os.environ.get("BENCH_FUSED_DP_DEVICES", 2))
FUSED_DP_TIMEOUT = float(os.environ.get("BENCH_FUSED_DP_TIMEOUT", 420.0))


def _fused_dp_child(dp: int) -> None:
    """Child body for one ``fused_dp`` width: a FusedApexTrainer at the
    given dp on the toy env, timed over warm dispatches.  One JSON line
    on stdout; the parent holds the hard timeout."""
    import jax

    from apex_tpu.config import (ActorConfig, ApexConfig, EnvConfig,
                                 LearnerConfig, ReplayConfig)
    from apex_tpu.ondevice.fused import FusedApexTrainer

    dispatches = int(os.environ.get("BENCH_FUSED_DP_STEPS", 8))
    spd = 2
    cfg = ApexConfig(
        env=EnvConfig(env_id="ApexCatchSmall-v0", frame_stack=2,
                      clip_rewards=False, episodic_life=False),
        replay=ReplayConfig(capacity=4096, warmup=256,
                            beta_anneal=50_000),
        learner=LearnerConfig(batch_size=64, compute_dtype="float32",
                              target_update_interval=500,
                              publish_interval=50, mesh_shape=(dp,)),
        actor=ActorConfig(n_actors=1, n_envs_per_actor=32,
                          send_interval=64))
    t = FusedApexTrainer(cfg, rollout_len=64, steps_per_dispatch=spd)
    t.train_state, t.replay_state, t.key, _ = t.fused.dispatch(
        t.train_state, t.replay_state, t.key)        # compile + warm
    base_steps = t.fused.train_steps
    base_trans = t.fused.transitions
    eng = t.fused.engine                             # full-width B
    t0 = time.perf_counter()
    for _ in range(dispatches):
        t.train_state, t.replay_state, t.key, _ = t.fused.dispatch(
            t.train_state, t.replay_state, t.key)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "dp": dp, "devices": jax.device_count(),
        "n_envs": eng.B, "rollout_len": eng.T,
        "steps_per_dispatch": spd, "dispatches": dispatches,
        "frames_per_sec":
            round(dispatches * spd * eng.T * eng.B / dt, 1),
        "train_steps_per_sec":
            round((t.fused.train_steps - base_steps) / dt, 2),
        "transitions_per_sec":
            round((t.fused.transitions - base_trans) / dt, 1),
        "seconds": round(dt, 2)}), flush=True)


def bench_fused_dp() -> dict:
    """Part 1f lane 4 ``fused_dp``: the whole fused training cycle
    (rollout + ingest + prioritized sample + train + write-back) at dp=1
    vs dp=N, each width in its own subprocess on a CPU mesh emulated via
    ``--xla_force_host_platform_device_count`` (so the forced device
    count never leaks into this process's backend).  Leaf names end in
    ``per_sec`` so the ``obs.slo --check`` differ classifies both widths
    higher-better automatically; on a 1-core box ``dp_speedup`` ~1.0 is
    the honest reading and ``effective_cores`` contextualizes it — the
    lane exists so a multi-core / TPU artifact shows the scaling."""
    n_dp = max(2, FUSED_DP_DEVICES)
    out: dict = {"n_dp": n_dp, "effective_cores": _effective_cores()}
    for label, dp in (("dp1", 1), ("dpN", n_dp)):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count"
                            f"={n_dp}")
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--fused-dp-child", str(dp)],
                capture_output=True, text=True,
                timeout=FUSED_DP_TIMEOUT, env=env)
        except subprocess.TimeoutExpired:
            out[label] = {"error":
                          f"fused_dp child exceeded {FUSED_DP_TIMEOUT}s"}
            continue
        lane = None
        for line in reversed(p.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                lane = json.loads(line)
                break
        out[label] = lane if lane is not None else {
            "error": (p.stderr or p.stdout
                      or "fused_dp child: no output")[-400:]}
    f1 = out["dp1"].get("frames_per_sec")
    fn = out["dpN"].get("frames_per_sec")
    out["dp_speedup"] = round(fn / f1, 2) if f1 and fn else None
    return out


# -- part 2: end-to-end pixel pipeline -------------------------------------

def _fleet_section(trainer) -> dict | None:
    """Fleet control-plane view of the e2e run (apex_tpu/fleet): state
    counts, heartbeat gap percentiles, and rejoin count from the same
    registry the socket learner serves on ``--role status`` — the in-host
    worker fleet beats over the stat queue, so the section is live even
    without sockets."""
    summary = trainer.fleet_summary()
    if summary is None:
        return None
    m = summary["metrics"]
    out = {"peers": m["peers"], "alive": m["alive"],
           "suspect": m["suspect"], "dead": m["dead"],
           "parked": m["parked"], "rejoins": m["rejoins"],
           "hb_gap_p50_s": m["hb_gap_p50_s"],
           "hb_gap_p99_s": m["hb_gap_p99_s"],
           "wire_rejected": m.get("wire_rejected", 0)}
    if "replay_service" in m:
        # sharded replay service (apex_tpu/replay_service): shard count,
        # batches pulled, write-back/fallback counters, per-shard status
        # — a chaos-killed shard's death is legible here next to the
        # registry's dead count above
        out["replay_service"] = m["replay_service"]
    return out


WIRE_CODEC_TIMEOUT = float(os.environ.get("BENCH_WIRE_CODEC_TIMEOUT", 240.0))


def bench_wire_codec() -> dict:
    """Part 1g: the chunk wire codec A/B (runtime/codec.py) on REAL env
    chunks — no synthetic arrays, the exact bytes an actor ships.

    Two payload families, each recorded once by driving the real env
    through the real ``FrameChunkBuilder`` and replayed through every
    codec:

    - ``catch``: ApexCatchSmall-v0 single frames (42x42 u8, ~sparse
      binary rendering — the near-binary regime the delta codec's
      XOR+RLE targets; issue target >=5x bytes/transition vs raw).
    - ``pixel``: ApexRally-v0 flagship frames (84x84 u8 — the
      dictionary codec's regime; issue target >=2x).

    Per codec x family: bytes/transition on the wire, compression ratio
    (raw pickle bytes / shipped bytes — >=1.0 by construction, the
    encoder ships raw whenever compression does not win), and
    encode/decode microseconds per chunk.  ``ingest`` replays the same
    encoded stream through :func:`codec.decode_chunk` back-to-back and
    reports frames/s — the fused-ingest decode cost the replay shard
    pays per chunk; the acceptance gate is delta within 10% of raw.
    """
    import pickle
    import time as _time

    import numpy as np

    from apex_tpu.config import EnvConfig
    from apex_tpu.envs.registry import make_env
    from apex_tpu.replay.frame_chunks import FrameChunkBuilder
    from apex_tpu.runtime import codec as wire_codec

    n_chunks = int(os.environ.get("BENCH_CODEC_CHUNKS", 24))
    chunk_k = int(os.environ.get("BENCH_CODEC_CHUNK_K", 64))

    def record(env_id: str) -> list[dict]:
        """Real chunk messages (payload + priorities + n_trans), exactly
        the dicts ChunkSender.send_chunk ships."""
        env = make_env(env_id, EnvConfig(env_id=env_id), seed=0,
                       stack_frames=False)
        rng = np.random.default_rng(0)
        obs, _ = env.reset(seed=0)
        builder = FrameChunkBuilder(3, 0.99, 4, np.asarray(obs).shape,
                                    chunk_transitions=chunk_k,
                                    frame_dtype=np.uint8)
        builder.begin_episode(np.asarray(obs))
        msgs: list[dict] = []
        n_act = env.action_space.n
        while len(msgs) < n_chunks:
            a = int(rng.integers(n_act))
            obs, r, term, trunc, _ = env.step(a)
            builder.add_step(a, float(r),
                             rng.normal(size=n_act).astype(np.float32),
                             np.asarray(obs), terminated=term,
                             truncated=trunc)
            if term or trunc:
                obs, _ = env.reset()
                builder.begin_episode(np.asarray(obs))
            for chunk in builder.poll():
                prios = chunk.pop("priorities")
                msgs.append({"payload": chunk, "priorities": prios,
                             "n_trans": int(chunk["n_trans"])})
        env.close()
        return msgs[:n_chunks]

    def measure(msgs: list[dict], codec: str) -> dict:
        wire_total = raw_total = trans_total = frames_total = 0
        enc_s = dec_s = 0.0
        encoded: list[bytes] = []
        for msg in msgs:
            t0 = _time.perf_counter()
            payload, raw_n, wire_n = wire_codec.encode_chunk(msg, codec)
            enc_s += _time.perf_counter() - t0
            encoded.append(payload)
            wire_total += wire_n
            raw_total += raw_n
            trans_total += int(msg["n_trans"])
            frames_total += int(msg["payload"]["n_frames"])
        for payload in encoded:
            # full receiver-side decode cost: the wire unpickle both
            # paths pay, plus decode_chunk for compressed payloads (the
            # fused-ingest path the decoder threads run).
            # in-process replay of a stream this bench pickled itself
            t0 = _time.perf_counter()
            # apexlint: disable=C005 -- same-process bench stream
            kind, body = pickle.loads(payload)
            if kind == "chunkc":
                wire_codec.decode_chunk(body)
            dec_s += _time.perf_counter() - t0
        n = len(msgs)
        return {"bytes_per_transition": round(wire_total / trans_total, 1),
                "codec_ratio": round(raw_total / wire_total, 2),
                "encode_us_per_chunk": round(1e6 * enc_s / n, 1),
                "decode_us_per_chunk": round(1e6 * dec_s / n, 1),
                "wire_bytes": wire_total, "raw_bytes": raw_total,
                "frames": frames_total}

    def loopback(msgs: list[dict], codec: str, reps: int = 6) -> float:
        """Receiver-side ingest frames/s through the REAL transport: a
        pre-encoded stream (the actor's seal-time encode cost is the
        separate encode_us column) pushed at a ChunkReceiver, whose
        decoder pool runs compressed decode fused with ingest, off the
        socket/ack thread — the acceptance gate compares this number
        delta-vs-raw."""
        import socket as _socket

        import zmq

        from apex_tpu.config import CommsConfig
        from apex_tpu.runtime.transport import ChunkReceiver, _ctx

        with _socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        comms = CommsConfig(batch_port=port)
        recv = ChunkReceiver(comms, bind_ip="127.0.0.1",
                             queue_depth=4 * len(msgs))
        recv.start()
        sock = _ctx().socket(zmq.DEALER)
        sock.setsockopt(zmq.IDENTITY, b"bench-codec")
        sock.connect(f"tcp://127.0.0.1:{port}")
        encoded = [wire_codec.encode_chunk(m, codec)[0] for m in msgs]
        frames_per_rep = sum(int(m["payload"]["n_frames"]) for m in msgs)
        window = 32          # saturating producer, bounded in-flight
        try:
            total = reps * len(msgs)

            def drain() -> None:    # backpressure relief: the trainer's
                for _ in range(total):      # poll_chunks stand-in
                    recv.chunks.get(timeout=30.0)

            drainer = threading.Thread(target=drain, daemon=True)
            in_flight = 0
            t0 = _time.perf_counter()
            drainer.start()
            for r in range(reps):
                for payload in encoded:
                    while in_flight >= window:
                        sock.recv()
                        in_flight -= 1
                    sock.send(payload)
                    in_flight += 1
            drainer.join(timeout=60.0)
            dt = _time.perf_counter() - t0
            if drainer.is_alive():
                raise RuntimeError("codec loopback drain stalled")
        finally:
            sock.close(linger=0)
            recv.stop()
        return round(reps * frames_per_rep / dt, 1)

    out: dict = {"chunks": n_chunks, "chunk_transitions": chunk_k}
    for family, env_id in (("catch", "ApexCatchSmall-v0"),
                           ("pixel", "ApexRally-v0")):
        msgs = record(env_id)
        section = {c: measure(msgs, c) for c in wire_codec.CODECS}
        # the acceptance gate: end-to-end ingest through the real
        # transport (sender encode + socket + decoder pool) within 10%
        # of the raw path over the identical stream
        raw_fps = loopback(msgs, "raw")
        delta_fps = loopback(msgs, "delta")
        section["ingest_frames_per_sec"] = {"raw": raw_fps,
                                            "delta": delta_fps}
        section["ingest_delta_vs_raw"] = (round(delta_fps / raw_fps, 3)
                                          if raw_fps else None)
        out[family] = section
    return out


def bench_end_to_end(e2e_seconds: float) -> dict:
    """The real ApexTrainer pipeline — vectorized actor processes feeding
    the fused learner through the shm chunk plane — on the PIXEL env
    ``ApexCatch-v0`` (84x84x4 uint8, flagship geometry) for
    ``e2e_seconds`` wall (the soak target plus the compile margin — see
    :func:`e2e_budgets`).  Runs with the async ingest pipeline at its
    config default, so the number measured is the shipping hot loop."""
    from apex_tpu.config import (ActorConfig, ApexConfig, EnvConfig,
                                 LearnerConfig, ReplayConfig)
    from apex_tpu.training.apex import ApexTrainer

    n_actors, n_envs = 4, 8          # 32 ladder slots in 4 processes
    env_id = os.environ.get("BENCH_E2E_ENV", "ApexCatch-v0")
    # scan dispatch in the live pipeline only on TPU (cf. part 1's gate:
    # the XLA:CPU conv-backward-in-loop pathology would throttle the
    # whole e2e run, not just skew one measurement)
    scan_steps = int(os.environ.get("BENCH_E2E_SCAN",
                                    4 if RESULT.get("platform") == "tpu"
                                    else 1))
    cfg = ApexConfig(
        env=EnvConfig(env_id=env_id, frame_stack=FRAME_STACK,
                      clip_rewards=False, episodic_life=False),
        replay=ReplayConfig(capacity=min(2 ** 15, CAPACITY),
                            warmup=min(2048, 4 * BATCH), frame_pool=True),
        learner=LearnerConfig(batch_size=BATCH, ingest_chunk=BATCH,
                              compute_dtype="bfloat16",
                              target_update_interval=500,
                              scan_steps=scan_steps),
        actor=ActorConfig(n_actors=n_actors, n_envs_per_actor=n_envs,
                          send_interval=64),
    )
    trainer = ApexTrainer(cfg, publish_min_seconds=0.5)
    from apex_tpu.native.ring import ShmChunkQueue
    data_plane = ("shm" if isinstance(trainer.pool.chunk_queue,
                                      ShmChunkQueue) else "mp.Queue")
    shape = trainer.replay.frame_shape
    stacked = shape[:-1] + (trainer.replay.frame_stack * shape[-1],)
    geometry = ("x".join(map(str, stacked))
                + "_" + trainer.replay.frame_dtype)
    # sample the monotone totals every 15s from a sidecar thread: the
    # consecutive-sample deltas give per-window steps/s, whose spread is
    # the soak's stability evidence (a sliding-window rate alone can't
    # show whether the run was steady or saw-toothed)
    samples: list[tuple[float, int, int]] = []
    sampler_stop = threading.Event()

    def _sampler() -> None:
        while not sampler_stop.wait(15.0):
            samples.append((time.monotonic(), trainer.steps_rate.total,
                            trainer.frames_rate.total))

    sampler = threading.Thread(target=_sampler, daemon=True)
    sampler.start()
    t0 = time.monotonic()
    try:
        trainer.train(total_steps=10 ** 9, max_seconds=e2e_seconds,
                      log_every=10 ** 9)
    finally:
        # always unpin: a still-sampling daemon would otherwise keep the
        # trainer (and its HBM replay ring) alive through the pallas stage
        sampler_stop.set()
    dt = time.monotonic() - t0

    # steady state = windows after the first one in which the learner
    # stepped (compile + replay warmup fill the preceding ones)
    windows = []
    steady_start = None
    for (ta, sa, _fa), (tb, sb, _fb) in zip(samples, samples[1:]):
        if sa > 0:
            if steady_start is None:
                steady_start = (ta, sa)
            windows.append((sb - sa) / (tb - ta))
    steady = None
    if steady_start is not None and samples and samples[-1][1] > steady_start[1]:
        t_first, s_first = steady_start
        t_last, s_last, _ = samples[-1]
        steady = {
            "steps_per_sec": round((s_last - s_first) / (t_last - t_first), 2),
            "seconds": round(t_last - t_first, 1),
            "windows": {"n": len(windows),
                        "min": round(min(windows), 2),
                        "p50": round(float(statistics.median(windows)), 2),
                        "max": round(max(windows), 2)} if windows else None,
        }

    # steady-state rates from the sliding tick windows — first-compile time
    # (~20-40s of the wall budget) would otherwise dominate the average
    return {"env": env_id,
            "steady": steady,
            # obs plane: frame-age-at-train / param-propagation-lag
            # histograms (p50/p90/p99) + hot-loop dispatch-gap percentiles
            "latency": trainer.latency_summary(),
            "obs_geometry": geometry,
            "env_frames_per_sec": round(trainer.frames_rate.rate, 1),
            "learner_steps_per_sec": round(trainer.steps_rate.rate, 2),
            "transitions_per_sec":
                round(trainer.steps_rate.rate * BATCH, 1),
            "total_frames": trainer.ingested,
            "total_steps": trainer.steps_rate.total,
            "actors": n_actors, "envs_per_actor": n_envs,
            "data_plane": data_plane,
            "scan_steps": scan_steps,
            "scan_dispatches": trainer.scan_dispatches,
            "actor_plane": trainer.actor_plane(),
            "fleet": _fleet_section(trainer),
            "ingest_pipeline": trainer._pipeline_last_stats,
            "dispatch_gap": (trainer._dispatch_gap.snapshot()
                             if trainer._dispatch_gap is not None else None),
            "seconds": round(dt, 1)}


def main() -> None:
    threading.Thread(target=_watchdog, daemon=True).start()

    global MEASURE_STEPS, REPS
    _arm("backend_probe", INIT_TIMEOUT + 60)
    platform = probe_backend()
    with _print_lock:
        RESULT["platform"] = platform
    if platform != "tpu":
        # CPU fallback at full batch/capacity is ~100x slower per step:
        # shrink the measurement loop so the diagnostic number still lands
        # inside the part-1 budget instead of tripping the watchdog
        # (explicit env overrides are honored)
        if "BENCH_STEPS" not in os.environ:
            MEASURE_STEPS = min(MEASURE_STEPS, 10)
        if "BENCH_REPS" not in os.environ:
            REPS = min(REPS, 2)
        if "BENCH_SCAN" not in os.environ:
            # scan dispatch is a TPU measurement; on XLA:CPU the conv
            # backward degrades ~20x inside while-loops (backend
            # artifact) and would burn minutes producing noise
            os.environ["BENCH_SCAN"] = "0"

    # Stage ordering is the round-4 lesson: the pallas kernel can wedge THE
    # DEVICE (an orphaned on-device DMA wait survives the probing process
    # and blocks every later client), so every guaranteed-safe measurement
    # runs FIRST on the XLA gather, and the pallas attempt comes LAST as a
    # strict upgrade — a wedge there loses nothing already recorded.
    operator_forced = os.environ.get("APEX_GATHER_MODE") not in (
        None, "", "auto")
    if not operator_forced:
        os.environ["APEX_GATHER_MODE"] = "xla"

    _arm("fused_step", PART1_TIMEOUT)
    fused = bench_fused_step()
    best = _best_variant(fused)
    bps = best["value"]               # raw median of the winning variant
    with _print_lock:
        RESULT.update(_headline_fields(best))
        RESULT["gather"] = fused["gather"]
        if fused.get("scan") is not None:
            RESULT["scan_part1"] = fused["scan"]
    # part 1 is safe from here on: even a part-2 hang emits it (watchdog)
    print(f"[bench] part 1 done: {json.dumps(RESULT)}",
          file=sys.stderr, flush=True)

    if os.environ.get("BENCH_SKIP_PIPELINE", "0") != "1":
        _arm("ingest_pipeline", PIPELINE_TIMEOUT)
        try:
            pipe = bench_ingest_pipeline()
        except Exception as exc:   # the headline metric survives regardless
            pipe = {"error": f"{type(exc).__name__}: {exc}"[:400]}
        with _print_lock:
            RESULT["ingest_pipeline"] = pipe
        # dp>1 variant of the same A/B, in its own emulated-mesh child —
        # a subprocess, so a hang or crash there costs only this field
        _arm("ingest_pipeline_dp", DP_PIPE_TIMEOUT + 30)
        with _print_lock:
            RESULT["ingest_pipeline_dp"] = bench_ingest_pipeline_dp()

    if os.environ.get("BENCH_SKIP_ACTOR_AB", "0") != "1":
        # part 1d: the actor-plane scheduling A/B (double-buffer on/off)
        _arm("actor_plane_ab", ACTOR_AB_TIMEOUT)
        try:
            ab = bench_actor_plane()
        except Exception as exc:   # the headline metric survives regardless
            ab = {"error": f"{type(exc).__name__}: {exc}"[:400]}
        with _print_lock:
            RESULT["actor_plane_ab"] = ab

    if os.environ.get("BENCH_SKIP_INFER_AB", "0") != "1":
        # part 1e: the inference-plane remote/local A/B (frames/s +
        # round-trip and coalesce percentiles + measured effective_cores,
        # machine-readable for CI upload and cross-box diffing)
        _arm("infer_plane_ab", INFER_AB_TIMEOUT)
        try:
            iab = bench_infer_plane()
        except Exception as exc:   # the headline metric survives regardless
            iab = {"error": f"{type(exc).__name__}: {exc}"[:400]}
        with _print_lock:
            RESULT["infer_plane_ab"] = iab

    if os.environ.get("BENCH_SKIP_ONDEVICE", "0") != "1":
        # part 1f: the fused on-device rollout engine vs the host
        # vector-actor path (frames/s at the default and width-matched
        # host topologies + sealed chunk/s into replay + effective_cores)
        _arm("ondevice_rollout_ab", ONDEVICE_AB_TIMEOUT)
        try:
            oab = bench_ondevice_rollout()
        except Exception as exc:   # the headline metric survives regardless
            oab = {"error": f"{type(exc).__name__}: {exc}"[:400]}
        with _print_lock:
            RESULT["ondevice_rollout_ab"] = oab

        # part 1f lane 4: the fused macro-step sharded over the dp mesh
        # (dp=1 vs dp=N subprocesses on an emulated CPU mesh)
        _arm("fused_dp", 2 * FUSED_DP_TIMEOUT + 60)
        try:
            fdp = bench_fused_dp()
        except Exception as exc:   # the headline metric survives regardless
            fdp = {"error": f"{type(exc).__name__}: {exc}"[:400]}
        with _print_lock:
            RESULT["fused_dp"] = fdp

    if os.environ.get("BENCH_SKIP_WIRE", "0") != "1":
        # part 1g: the chunk wire codec A/B on real Catch/Rally chunks
        # (bytes/transition, compression ratio, encode/decode us, fused
        # decode frames/s vs the raw unpickle)
        _arm("wire_codec", WIRE_CODEC_TIMEOUT)
        try:
            wc = bench_wire_codec()
        except Exception as exc:   # the headline metric survives regardless
            wc = {"error": f"{type(exc).__name__}: {exc}"[:400]}
        with _print_lock:
            RESULT["wire_codec"] = wc

    # Late backend re-probe between part 1 and the e2e soak: a relay that
    # warmed up after the t=0 probe re-execs the bench onto the TPU
    # instead of burning the round on CPU fallback numbers.
    _arm("late_reprobe", INIT_TIMEOUT + 30)
    maybe_reprobe(platform)

    soak, e2e_train_seconds, e2e_stage_seconds = e2e_budgets(platform)
    _arm("e2e", e2e_stage_seconds)
    try:
        e2e = bench_end_to_end(e2e_train_seconds)
    except Exception as exc:      # never lose the primary metric
        e2e = {"error": f"{type(exc).__name__}: {exc}"}
    with _print_lock:
        RESULT["e2e"] = e2e
        RESULT["e2e_budgets"] = {"soak": soak, "train": e2e_train_seconds,
                                 "stage": e2e_stage_seconds}

    if (platform == "tpu" and not operator_forced
            and os.environ.get("BENCH_SKIP_PALLAS", "0") != "1"):
        # a hang anywhere in this stage trips the watchdog, which emits
        # everything recorded above and exits 0 — the attempt is a strict
        # upgrade, never a risk to the XLA numbers
        _arm("pallas_probe", PALLAS_PROBE_TIMEOUT)
        err = probe_pallas()       # sets APEX_GATHER_MODE=xla on failure
        if err is not None:
            with _print_lock:
                RESULT["pallas_error"] = err
        else:
            os.environ["APEX_GATHER_MODE"] = "pallas"
            _arm("fused_step_pallas", PART1_TIMEOUT)
            try:
                pf = bench_fused_step()
                pbest = _best_variant(pf)
                with _print_lock:
                    RESULT["pallas_part1"] = {
                        "value": round(pf["median"], 2),
                        "spread": {"min": pf["min"], "max": pf["max"],
                                   "reps": pf["reps"]},
                        "scan": pf.get("scan"), "mfu": pf["mfu"]}
                    # compare raw medians — the rounded RESULT["value"]
                    # could flip a sub-0.01 loss into a "win"
                    if pbest["value"] > bps:             # strict upgrade
                        RESULT.update(_headline_fields(pbest))
                        RESULT["gather"] = "pallas"
            except Exception as exc:
                with _print_lock:
                    RESULT["pallas_error"] = (
                        f"fused step: {type(exc).__name__}: {exc}"[:400])

    _finish()


def _best_variant(fused: dict) -> dict:
    """The faster of the single-dispatch and scan-dispatch measurements
    from one :func:`bench_fused_step` result, as headline-ready fields
    (``value`` stays the RAW median so comparisons never hinge on
    rounding)."""
    scan = fused.get("scan")
    if scan is not None and scan["median"] > fused["median"]:
        return dict(value=scan["median"],
                    spread={"min": scan["min"], "max": scan["max"],
                            "reps": fused["reps"]},
                    mfu=scan["mfu"], dispatch=f"scan{scan['k']}")
    return dict(value=fused["median"],
                spread={"min": fused["min"], "max": fused["max"],
                        "reps": fused["reps"]},
                mfu=fused["mfu"], dispatch="single")


def _headline_fields(best: dict) -> dict:
    return {"value": round(best["value"], 2),
            "vs_baseline": round(best["value"] / BASELINE_BPS, 2),
            "spread": best["spread"], "mfu": best["mfu"],
            "dispatch": best["dispatch"]}


def _finish() -> None:
    _stage["deadline"] = None
    _done.set()
    # same emitter as the watchdog/crash paths; os._exit because actor
    # worker processes may still be tearing down and a wedged child must
    # not hold the exit after the JSON line is out
    _emit_and_exit()


if __name__ == "__main__":
    if "--dp-pipe-child" in sys.argv:
        _dp_pipe_child()           # one JSON line; no watchdog, the
        sys.exit(0)                # parent holds the hard timeout
    if "--fused-dp-child" in sys.argv:
        _fused_dp_child(int(sys.argv[sys.argv.index("--fused-dp-child")
                                     + 1]))
        sys.exit(0)                # parent holds the hard timeout
    try:
        main()
    except BaseException as exc:   # a CRASH (vs hang) must also emit the
        import traceback           # accumulated partial JSON, not a bare
        traceback.print_exc()      # traceback with rc != 0
        RESULT.setdefault("error", f"{type(exc).__name__}: {exc}"[:400])
        _emit_and_exit()
