"""Headline benchmark: learner throughput on one TPU chip + end-to-end rates.

Reference baseline: 10-12 batches/s at batch 512 on a V100 learner fed by a
separate replay server (``origin_repo/README.md:42``; BASELINE.md).  Part 1
measures the SAME unit of work, harder: each learner step here also ingests
512 fresh transitions and performs the PER priority write-back on-device —
work the reference offloads to its replay server — fused into one XLA
program on the Atari-shape DuelingDQN (84x84x4 uint8 stacks, batch 512),
repeated ``REPS`` times for a spread.

Part 2 runs the REAL concurrent pipeline (ApexTrainer + vectorized actor
processes over the shm data plane) on the PIXEL env ``ApexCatch-v0``
(84x84x4 uint8, the flagship geometry — the numpy renderer stands in for
ALE, absent in this image) to measure env-frames/sec ingested and
learner-steps/sec sustained end to end, queue/staging/publish overhead
included.

Replay is the frame-pool layout: 2^19 transitions + 2^20 single frames
resident in HBM (~7.5GB/chip); an 8-chip slice with per-chip shards doubles
the reference's 2e6 total capacity.  Stacks are gathered on device at
sample time.

Part 1 measures two dispatch shapes: one fused step per host round-trip
("single") and a ``lax.scan`` of BENCH_SCAN=8 bit-identical steps per
round-trip ("scanK" — host dispatch is the dominant per-step overhead on
relay-backed chips); the headline takes the faster, with both recorded.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} plus
"spread" (min/max over reps), "mfu", "gather" (the row-gather path actually
used), "dispatch" ("single" | "scanK"), "platform", and "e2e" (the
ApexTrainer rates).
vs_baseline = value / 11.0 (midpoint of the reference's 10-12 range).

Hang hardening (round 3 lost its only on-chip number to a silent 25-minute
stall, rc=124, no JSON; round 4's first live run then lost both parts to a
pallas probe that wedged the DEVICE): the TPU is reached through a relay
that can dial slowly or never, so

* backend init is probed in a SUBPROCESS with a hard timeout first — if the
  platform never comes up, the main process optionally falls back to CPU
  (``platform`` field records which; ``BENCH_CPU_FALLBACK=0`` disables);
* a watchdog thread arms a deadline per stage and, when one is missed,
  prints the accumulated partial result as the final JSON line and exits 0
  — a part-2 hang can no longer lose part 1;
* parts 1 and 2 run on the guaranteed-safe XLA gather FIRST; the pallas
  kernel is attempted LAST (in-process — the relay chip is single-client,
  so a subprocess could not attach — probe, then a part-1 rerun taken as
  a strict upgrade) because a wedged on-device kernel outlives its
  process and blocks every subsequent client.  A hang in this final stage
  trips the watchdog, which emits all the already-recorded numbers and
  exits 0; failures land in ``pallas_error``; ``BENCH_SKIP_PALLAS=1``
  skips the attempt entirely.
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import threading
import time

BASELINE_BPS = 11.0
BATCH = int(os.environ.get("BENCH_BATCH", 512))
FRAME_SHAPE = (84, 84, 1)
FRAME_STACK = 4
CAPACITY = int(os.environ.get("BENCH_CAPACITY", 2 ** 19))
FRAME_CAPACITY = 2 * CAPACITY
CHUNK = BATCH          # transitions ingested per fused step
CHUNK_FRAMES = CHUNK + 16
WARMUP_STEPS = 3
# env overrides let CI smoke-test the bench on CPU at toy scale; the
# driver's real-chip run uses the defaults
MEASURE_STEPS = int(os.environ.get("BENCH_STEPS", 50))
REPS = int(os.environ.get("BENCH_REPS", 3))
# first TPU compile of the concurrent pipeline eats ~20-40s of this wall
# budget and the 2048-transition warmup a further slice; the steady-state
# window after both is what the sliding rate counters report.  On TPU the
# e2e stage is a SOAK: >=300s wall so that >=180s of post-compile steady
# state is measured (round numbers must not be a 37-step sliver); the CPU
# diagnostic lane keeps the short default.
def _e2e_seconds(platform: str) -> float:
    if "BENCH_E2E_SECONDS" in os.environ:
        return float(os.environ["BENCH_E2E_SECONDS"])
    return 300.0 if platform == "tpu" else 120.0


# stage deadlines (watchdog): generous but finite — the whole bench must
# land inside the driver's outer timeout with the JSON line printed
INIT_TIMEOUT = float(os.environ.get("BENCH_INIT_TIMEOUT", 240.0))
PART1_TIMEOUT = float(os.environ.get("BENCH_PART1_TIMEOUT", 360.0))
PART2_MARGIN = float(os.environ.get("BENCH_PART2_MARGIN", 240.0))

# -- watchdog ---------------------------------------------------------------

RESULT: dict = {
    "metric": f"learner_batches_per_sec_batch{BATCH}_framepool_per_ingest",
    "value": None, "unit": "batches/s", "vs_baseline": None,
}
_stage = {"name": "start", "deadline": None}
_done = threading.Event()
_print_lock = threading.Lock()


def _emit_and_exit() -> None:
    # _print_lock also guards RESULT mutations (main thread), so the dump
    # cannot race a concurrent insert; the dict(...) copy is belt-and-braces
    with _print_lock:
        print(json.dumps(dict(RESULT)), flush=True)
    os._exit(0)          # watchdog path: threads/children may be wedged


def _arm(name: str, seconds: float) -> None:
    _stage["name"] = name
    _stage["deadline"] = time.monotonic() + seconds
    print(f"[bench] stage {name} (budget {seconds:.0f}s)",
          file=sys.stderr, flush=True)


def _watchdog() -> None:
    while not _done.wait(2.0):
        dl = _stage["deadline"]
        if dl is not None and time.monotonic() > dl:
            RESULT["error"] = (f"watchdog: stage {_stage['name']!r} "
                               f"exceeded its budget")
            _emit_and_exit()


# -- stage 0: backend probe -------------------------------------------------

def _apply_platform() -> None:
    """Make an explicit ``JAX_PLATFORMS`` stick in the CURRENT process:
    the axon plugin registers at interpreter start (sitecustomize) and
    ignores the env var, so it must be applied via jax.config — the env
    var alone would leave CI's cpu choice spinning on a dead relay.  Safe
    only before the backend is first initialized (true for every caller:
    the main process has not touched jax yet)."""
    p = os.environ.get("JAX_PLATFORMS")
    if p:
        import jax
        jax.config.update("jax_platforms", p)


# the same trick, inlined into the probe subprocess's -c code
_APPLY_PLATFORM_CODE = (
    "import os, jax; p = os.environ.get('JAX_PLATFORMS'); "
    "p and jax.config.update('jax_platforms', p); ")


def probe_backend() -> str:
    """Bring the backend up in a SUBPROCESS first: a dead relay makes
    ``jax.devices()`` spin forever, and a subprocess can be killed where
    the main process cannot un-hang itself.  Returns the platform the main
    process should use ("tpu"/"cpu"/...)."""
    code = (_APPLY_PLATFORM_CODE +
            "import jax.numpy as jnp; "
            "d = jax.devices(); "
            "(jnp.ones((256, 256), jnp.bfloat16) @ "
            "jnp.ones((256, 256), jnp.bfloat16)).block_until_ready(); "
            "print('PLATFORM=' + d[0].platform)")
    try:
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=INIT_TIMEOUT)
        for line in p.stdout.splitlines():
            if line.startswith("PLATFORM="):
                _apply_platform()   # mirror the choice the probe made
                return line.split("=", 1)[1]
        with _print_lock:
            RESULT["backend_probe"] = (p.stderr or p.stdout or "")[-400:]
    except subprocess.TimeoutExpired:
        with _print_lock:
            RESULT["backend_probe"] = (
                f"backend init exceeded {INIT_TIMEOUT}s")
    if os.environ.get("BENCH_CPU_FALLBACK", "1") != "0":
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        _apply_platform()
        return "cpu"
    RESULT["error"] = RESULT.get("backend_probe", "backend unavailable")
    _emit_and_exit()
    raise AssertionError  # unreachable


# -- final stage: pallas kernel probe ---------------------------------------

PALLAS_PROBE_TIMEOUT = float(os.environ.get("BENCH_PALLAS_TIMEOUT", 150.0))


def probe_pallas() -> str | None:
    """Compile + run the standalone gather kernel on the real chip.

    Runs IN-PROCESS (the relay chip is single-client, so a subprocess
    could never attach while the bench still holds the backend) and LAST
    (the round-4 live run showed a misbehaving kernel doesn't just fail —
    it can wedge the device for every later client).  By this point every
    safe number is already in RESULT, so a hang here is caught by the
    watchdog, which emits the accumulated JSON and exits 0: the hang
    costs only the pallas upgrade.  Failures land in ``pallas_error``
    rather than silently falling back (VERDICT r3 weak #1)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.gather import ROW_UNIT, _pallas_gather

    try:
        f = 64
        f3 = (jnp.arange(f * ROW_UNIT, dtype=jnp.int32) % 251
              ).astype(jnp.uint8).reshape(f, 8, ROW_UNIT // 8)
        ids = jnp.array([3, 1, 63, 0, 17, 3, 62, 9], jnp.int32)
        out = jax.block_until_ready(_pallas_gather(f3, ids))
        ref = jnp.take(f3.reshape(f, -1), ids, axis=0)
        if not bool(jnp.array_equal(out, ref)):
            raise RuntimeError("on-chip pallas gather != XLA gather")
        return None
    except Exception as exc:
        os.environ["APEX_GATHER_MODE"] = "xla"
        return f"{type(exc).__name__}: {exc}"[:400]


# -- part 1: fused learner step --------------------------------------------

def _synthetic_chunk(rng):
    """A representative actor chunk: CHUNK transitions over CHUNK_FRAMES
    contiguous frames, stacks referencing chunk-relative windows."""
    import numpy as np
    d = int(np.prod(FRAME_SHAPE))
    base = np.minimum(np.arange(CHUNK), CHUNK_FRAMES - 1 - 3)
    offs = np.arange(-(FRAME_STACK - 1), 1)
    obs_ref = np.maximum(base[:, None] + offs[None, :], 0).astype(np.int32)
    next_ref = np.minimum(obs_ref + 3, CHUNK_FRAMES - 1).astype(np.int32)
    chunk = dict(
        frames=rng.integers(0, 255, (CHUNK_FRAMES, d)).astype(np.uint8),
        n_frames=np.int32(CHUNK_FRAMES),
        n_trans=np.int32(CHUNK),
        action=rng.integers(0, 6, CHUNK).astype(np.int32),
        reward=rng.normal(size=CHUNK).astype(np.float32),
        discount=np.full(CHUNK, 0.99 ** 3, np.float32),
        obs_ref=obs_ref,
        next_ref=next_ref,
    )
    prios = np.abs(rng.normal(size=CHUNK)).astype(np.float32) + 1e-3
    return chunk, prios


def bench_fused_step() -> dict:
    """The fused ingest+sample+update+write-back step, pre-staged device
    inputs, REPS timed repetitions."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.models.dueling import DuelingDQN
    from apex_tpu.ops.gather import resolved_mode
    from apex_tpu.ops.losses import make_optimizer
    from apex_tpu.replay.frame_pool import FramePoolReplay
    from apex_tpu.training.learner import LearnerCore
    from apex_tpu.training.state import create_train_state

    model = DuelingDQN(num_actions=6)
    pool = FramePoolReplay(capacity=CAPACITY, frame_shape=FRAME_SHAPE,
                           frame_stack=FRAME_STACK,
                           frame_capacity=FRAME_CAPACITY)
    optimizer = make_optimizer()
    ts = create_train_state(
        model, optimizer, jax.random.key(0),
        jnp.zeros((1, 84, 84, FRAME_STACK), jnp.uint8))
    core = LearnerCore(apply_fn=model.apply, replay=pool,
                       optimizer=optimizer, batch_size=BATCH,
                       target_update_interval=2500)
    rs = pool.init()
    gather = resolved_mode(rs.frames, pool.gather_mode)

    rng = np.random.default_rng(0)
    chunk, prios = _synthetic_chunk(rng)
    chunk = jax.device_put(chunk)
    prios = jax.device_put(jnp.asarray(prios))

    fused = core.jit_fused_step()
    for i in range(WARMUP_STEPS):
        ts, rs, metrics = fused(ts, rs, chunk, prios, jax.random.key(i),
                                jnp.float32(0.4))
    jax.block_until_ready(metrics["loss"])

    rates = []
    for rep in range(REPS):
        t0 = time.perf_counter()
        for i in range(MEASURE_STEPS):
            ts, rs, metrics = fused(ts, rs, chunk, prios,
                                    jax.random.key(1000 * rep + i),
                                    jnp.float32(0.4))
        jax.block_until_ready(metrics["loss"])
        rates.append(MEASURE_STEPS / (time.perf_counter() - t0))

    from apex_tpu.utils.profiling import DEFAULT_PEAK, flops_per_call, mfu
    flops = flops_per_call(fused, ts, rs, chunk, prios, jax.random.key(0),
                           jnp.float32(0.4))
    peak = (float(os.environ["BENCH_PEAK_TFLOPS"]) * 1e12
            if "BENCH_PEAK_TFLOPS" in os.environ else DEFAULT_PEAK)
    util = mfu(flops, float(np.median(rates)), peak)
    out = {"median": float(np.median(rates)),
           "min": round(min(rates), 2), "max": round(max(rates), 2),
           "reps": REPS, "gather": gather,
           "mfu": None if util is None else round(util, 4)}

    # scan-of-K dispatch: same per-step program (tests pin bit-parity),
    # K fewer host round-trips — the dominant overhead on relay-backed
    # chips.  Reported per-STEP so the unit stays comparable.  main()
    # zeroes BENCH_SCAN on non-TPU platforms: XLA:CPU lowers the conv
    # backward ~20x slower inside while-loops (measured), so a CPU scan
    # number is a backend artifact, not a signal.
    k = int(os.environ.get("BENCH_SCAN", 8))
    if k > 1:
        multi = core.jit_fused_multi_step()
        stacked = jax.device_put(jax.tree.map(
            lambda x: jnp.stack([jnp.asarray(x)] * k), chunk))
        sprios = jax.device_put(jnp.stack([jnp.asarray(prios)] * k))
        n_dispatch = max(1, MEASURE_STEPS // k)
        keys = jax.random.split(jax.random.key(7), k)
        ts, rs, m = multi(ts, rs, stacked, sprios, keys, jnp.float32(0.4))
        jax.block_until_ready(m["loss"])              # compile + warm
        scan_rates = []
        for rep in range(REPS):
            t0 = time.perf_counter()
            for i in range(n_dispatch):
                keys = jax.random.split(
                    jax.random.key(5000 + 1000 * rep + i), k)
                ts, rs, m = multi(ts, rs, stacked, sprios, keys,
                                  jnp.float32(0.4))
            jax.block_until_ready(m["loss"])
            scan_rates.append(n_dispatch * k
                              / (time.perf_counter() - t0))
        # apexlint: disable=J004 -- flops probe re-invokes with measurement-only keys
        sflops = flops_per_call(multi, ts, rs, stacked, sprios, keys,
                                jnp.float32(0.4))
        sutil = mfu(None if sflops is None else sflops / k,
                    float(np.median(scan_rates)), peak)
        out["scan"] = {"k": k, "median": float(np.median(scan_rates)),
                       "min": round(min(scan_rates), 2),
                       "max": round(max(scan_rates), 2),
                       "mfu": None if sutil is None else round(sutil, 4)}
    return out


# -- part 2: end-to-end pixel pipeline -------------------------------------

def bench_end_to_end(e2e_seconds: float) -> dict:
    """The real ApexTrainer pipeline — vectorized actor processes feeding
    the fused learner through the shm chunk plane — on the PIXEL env
    ``ApexCatch-v0`` (84x84x4 uint8, flagship geometry) for
    ``e2e_seconds`` (a >=300s soak on TPU, see :func:`_e2e_seconds`)."""
    from apex_tpu.config import (ActorConfig, ApexConfig, EnvConfig,
                                 LearnerConfig, ReplayConfig)
    from apex_tpu.training.apex import ApexTrainer

    n_actors, n_envs = 4, 8          # 32 ladder slots in 4 processes
    env_id = os.environ.get("BENCH_E2E_ENV", "ApexCatch-v0")
    # scan dispatch in the live pipeline only on TPU (cf. part 1's gate:
    # the XLA:CPU conv-backward-in-loop pathology would throttle the
    # whole e2e run, not just skew one measurement)
    scan_steps = int(os.environ.get("BENCH_E2E_SCAN",
                                    4 if RESULT.get("platform") == "tpu"
                                    else 1))
    cfg = ApexConfig(
        env=EnvConfig(env_id=env_id, frame_stack=FRAME_STACK,
                      clip_rewards=False, episodic_life=False),
        replay=ReplayConfig(capacity=min(2 ** 15, CAPACITY),
                            warmup=min(2048, 4 * BATCH), frame_pool=True),
        learner=LearnerConfig(batch_size=BATCH, ingest_chunk=BATCH,
                              compute_dtype="bfloat16",
                              target_update_interval=500,
                              scan_steps=scan_steps),
        actor=ActorConfig(n_actors=n_actors, n_envs_per_actor=n_envs,
                          send_interval=64),
    )
    trainer = ApexTrainer(cfg, publish_min_seconds=0.5)
    from apex_tpu.native.ring import ShmChunkQueue
    data_plane = ("shm" if isinstance(trainer.pool.chunk_queue,
                                      ShmChunkQueue) else "mp.Queue")
    shape = trainer.replay.frame_shape
    stacked = shape[:-1] + (trainer.replay.frame_stack * shape[-1],)
    geometry = ("x".join(map(str, stacked))
                + "_" + trainer.replay.frame_dtype)
    # sample the monotone totals every 15s from a sidecar thread: the
    # consecutive-sample deltas give per-window steps/s, whose spread is
    # the soak's stability evidence (a sliding-window rate alone can't
    # show whether the run was steady or saw-toothed)
    samples: list[tuple[float, int, int]] = []
    sampler_stop = threading.Event()

    def _sampler() -> None:
        while not sampler_stop.wait(15.0):
            samples.append((time.monotonic(), trainer.steps_rate.total,
                            trainer.frames_rate.total))

    sampler = threading.Thread(target=_sampler, daemon=True)
    sampler.start()
    t0 = time.monotonic()
    try:
        trainer.train(total_steps=10 ** 9, max_seconds=e2e_seconds,
                      log_every=10 ** 9)
    finally:
        # always unpin: a still-sampling daemon would otherwise keep the
        # trainer (and its HBM replay ring) alive through the pallas stage
        sampler_stop.set()
    dt = time.monotonic() - t0

    # steady state = windows after the first one in which the learner
    # stepped (compile + replay warmup fill the preceding ones)
    windows = []
    steady_start = None
    for (ta, sa, _fa), (tb, sb, _fb) in zip(samples, samples[1:]):
        if sa > 0:
            if steady_start is None:
                steady_start = (ta, sa)
            windows.append((sb - sa) / (tb - ta))
    steady = None
    if steady_start is not None and samples and samples[-1][1] > steady_start[1]:
        t_first, s_first = steady_start
        t_last, s_last, _ = samples[-1]
        steady = {
            "steps_per_sec": round((s_last - s_first) / (t_last - t_first), 2),
            "seconds": round(t_last - t_first, 1),
            "windows": {"n": len(windows),
                        "min": round(min(windows), 2),
                        "p50": round(float(statistics.median(windows)), 2),
                        "max": round(max(windows), 2)} if windows else None,
        }

    # steady-state rates from the sliding tick windows — first-compile time
    # (~20-40s of the wall budget) would otherwise dominate the average
    return {"env": env_id,
            "steady": steady,
            "obs_geometry": geometry,
            "env_frames_per_sec": round(trainer.frames_rate.rate, 1),
            "learner_steps_per_sec": round(trainer.steps_rate.rate, 2),
            "transitions_per_sec":
                round(trainer.steps_rate.rate * BATCH, 1),
            "total_frames": trainer.ingested,
            "total_steps": trainer.steps_rate.total,
            "actors": n_actors, "envs_per_actor": n_envs,
            "data_plane": data_plane,
            "scan_steps": scan_steps,
            "scan_dispatches": trainer.scan_dispatches,
            "seconds": round(dt, 1)}


def main() -> None:
    threading.Thread(target=_watchdog, daemon=True).start()

    global MEASURE_STEPS, REPS
    _arm("backend_probe", INIT_TIMEOUT + 60)
    platform = probe_backend()
    with _print_lock:
        RESULT["platform"] = platform
    if platform != "tpu":
        # CPU fallback at full batch/capacity is ~100x slower per step:
        # shrink the measurement loop so the diagnostic number still lands
        # inside the part-1 budget instead of tripping the watchdog
        # (explicit env overrides are honored)
        if "BENCH_STEPS" not in os.environ:
            MEASURE_STEPS = min(MEASURE_STEPS, 10)
        if "BENCH_REPS" not in os.environ:
            REPS = min(REPS, 2)
        if "BENCH_SCAN" not in os.environ:
            # scan dispatch is a TPU measurement; on XLA:CPU the conv
            # backward degrades ~20x inside while-loops (backend
            # artifact) and would burn minutes producing noise
            os.environ["BENCH_SCAN"] = "0"

    # Stage ordering is the round-4 lesson: the pallas kernel can wedge THE
    # DEVICE (an orphaned on-device DMA wait survives the probing process
    # and blocks every later client), so every guaranteed-safe measurement
    # runs FIRST on the XLA gather, and the pallas attempt comes LAST as a
    # strict upgrade — a wedge there loses nothing already recorded.
    operator_forced = os.environ.get("APEX_GATHER_MODE") not in (
        None, "", "auto")
    if not operator_forced:
        os.environ["APEX_GATHER_MODE"] = "xla"

    _arm("fused_step", PART1_TIMEOUT)
    fused = bench_fused_step()
    best = _best_variant(fused)
    bps = best["value"]               # raw median of the winning variant
    with _print_lock:
        RESULT.update(_headline_fields(best))
        RESULT["gather"] = fused["gather"]
        if fused.get("scan") is not None:
            RESULT["scan_part1"] = fused["scan"]
    # part 1 is safe from here on: even a part-2 hang emits it (watchdog)
    print(f"[bench] part 1 done: {json.dumps(RESULT)}",
          file=sys.stderr, flush=True)

    e2e_seconds = _e2e_seconds(platform)
    _arm("e2e", e2e_seconds + PART2_MARGIN)
    try:
        e2e = bench_end_to_end(e2e_seconds)
    except Exception as exc:      # never lose the primary metric
        e2e = {"error": f"{type(exc).__name__}: {exc}"}
    with _print_lock:
        RESULT["e2e"] = e2e

    if (platform == "tpu" and not operator_forced
            and os.environ.get("BENCH_SKIP_PALLAS", "0") != "1"):
        # a hang anywhere in this stage trips the watchdog, which emits
        # everything recorded above and exits 0 — the attempt is a strict
        # upgrade, never a risk to the XLA numbers
        _arm("pallas_probe", PALLAS_PROBE_TIMEOUT)
        err = probe_pallas()       # sets APEX_GATHER_MODE=xla on failure
        if err is not None:
            with _print_lock:
                RESULT["pallas_error"] = err
        else:
            os.environ["APEX_GATHER_MODE"] = "pallas"
            _arm("fused_step_pallas", PART1_TIMEOUT)
            try:
                pf = bench_fused_step()
                pbest = _best_variant(pf)
                with _print_lock:
                    RESULT["pallas_part1"] = {
                        "value": round(pf["median"], 2),
                        "spread": {"min": pf["min"], "max": pf["max"],
                                   "reps": pf["reps"]},
                        "scan": pf.get("scan"), "mfu": pf["mfu"]}
                    # compare raw medians — the rounded RESULT["value"]
                    # could flip a sub-0.01 loss into a "win"
                    if pbest["value"] > bps:             # strict upgrade
                        RESULT.update(_headline_fields(pbest))
                        RESULT["gather"] = "pallas"
            except Exception as exc:
                with _print_lock:
                    RESULT["pallas_error"] = (
                        f"fused step: {type(exc).__name__}: {exc}"[:400])

    _finish()


def _best_variant(fused: dict) -> dict:
    """The faster of the single-dispatch and scan-dispatch measurements
    from one :func:`bench_fused_step` result, as headline-ready fields
    (``value`` stays the RAW median so comparisons never hinge on
    rounding)."""
    scan = fused.get("scan")
    if scan is not None and scan["median"] > fused["median"]:
        return dict(value=scan["median"],
                    spread={"min": scan["min"], "max": scan["max"],
                            "reps": fused["reps"]},
                    mfu=scan["mfu"], dispatch=f"scan{scan['k']}")
    return dict(value=fused["median"],
                spread={"min": fused["min"], "max": fused["max"],
                        "reps": fused["reps"]},
                mfu=fused["mfu"], dispatch="single")


def _headline_fields(best: dict) -> dict:
    return {"value": round(best["value"], 2),
            "vs_baseline": round(best["value"] / BASELINE_BPS, 2),
            "spread": best["spread"], "mfu": best["mfu"],
            "dispatch": best["dispatch"]}


def _finish() -> None:
    _stage["deadline"] = None
    _done.set()
    # same emitter as the watchdog/crash paths; os._exit because actor
    # worker processes may still be tearing down and a wedged child must
    # not hold the exit after the JSON line is out
    _emit_and_exit()


if __name__ == "__main__":
    try:
        main()
    except BaseException as exc:   # a CRASH (vs hang) must also emit the
        import traceback           # accumulated partial JSON, not a bare
        traceback.print_exc()      # traceback with rc != 0
        RESULT.setdefault("error", f"{type(exc).__name__}: {exc}"[:400])
        _emit_and_exit()
