"""Headline benchmark: learner batches/sec on one TPU chip.

Reference baseline: 10-12 batches/s at batch 512 on a V100 learner fed by a
separate replay server (``origin_repo/README.md:42``; BASELINE.md).  We
measure the SAME unit of work, harder: each learner step here also ingests
512 fresh transitions and performs the PER priority write-back on-device —
work the reference offloads to its replay server — fused into one XLA
program on the Atari-shape DuelingDQN (84x84x4 uint8 stacks, batch 512).

Replay is the frame-pool layout (apex_tpu/replay/frame_pool.py): 2^19
transitions + 2^20 single frames resident in HBM (~7.5GB).  Per chip that
is ~a quarter of the reference's 2e6-transition replay host; an 8-chip
slice with per-chip shards doubles the reference's total capacity.  Stacks
are gathered on device at sample time.

Prints one JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = value / 11.0 (midpoint of the reference's 10-12 range).
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_BPS = 11.0
BATCH = 512
FRAME_SHAPE = (84, 84, 1)
FRAME_STACK = 4
CAPACITY = 2 ** 19
FRAME_CAPACITY = 2 ** 20
CHUNK = 512            # transitions ingested per fused step
CHUNK_FRAMES = 512 + 16
WARMUP_STEPS = 3
MEASURE_STEPS = 50


def _synthetic_chunk(rng: np.random.Generator) -> tuple[dict, np.ndarray]:
    """A representative actor chunk: CHUNK transitions over CHUNK_FRAMES
    contiguous frames, stacks referencing chunk-relative windows."""
    d = int(np.prod(FRAME_SHAPE))
    base = np.minimum(np.arange(CHUNK), CHUNK_FRAMES - 1 - 3)
    offs = np.arange(-(FRAME_STACK - 1), 1)
    obs_ref = np.maximum(base[:, None] + offs[None, :], 0).astype(np.int32)
    next_ref = np.minimum(obs_ref + 3, CHUNK_FRAMES - 1).astype(np.int32)
    chunk = dict(
        frames=rng.integers(0, 255, (CHUNK_FRAMES, d)).astype(np.uint8),
        n_frames=np.int32(CHUNK_FRAMES),
        n_trans=np.int32(CHUNK),
        action=rng.integers(0, 6, CHUNK).astype(np.int32),
        reward=rng.normal(size=CHUNK).astype(np.float32),
        discount=np.full(CHUNK, 0.99 ** 3, np.float32),
        obs_ref=obs_ref,
        next_ref=next_ref,
    )
    prios = np.abs(rng.normal(size=CHUNK)).astype(np.float32) + 1e-3
    return chunk, prios


def main() -> None:
    from apex_tpu.models.dueling import DuelingDQN
    from apex_tpu.ops.losses import make_optimizer
    from apex_tpu.replay.frame_pool import FramePoolReplay
    from apex_tpu.training.learner import LearnerCore
    from apex_tpu.training.state import create_train_state

    model = DuelingDQN(num_actions=6)
    pool = FramePoolReplay(capacity=CAPACITY, frame_shape=FRAME_SHAPE,
                           frame_stack=FRAME_STACK,
                           frame_capacity=FRAME_CAPACITY)
    optimizer = make_optimizer()
    ts = create_train_state(
        model, optimizer, jax.random.key(0),
        jnp.zeros((1, 84, 84, FRAME_STACK), jnp.uint8))
    core = LearnerCore(apply_fn=model.apply, replay=pool,
                       optimizer=optimizer, batch_size=BATCH,
                       target_update_interval=2500)
    rs = pool.init()

    rng = np.random.default_rng(0)
    chunk, prios = _synthetic_chunk(rng)
    chunk = jax.device_put(chunk)
    prios = jax.device_put(jnp.asarray(prios))

    fused = core.jit_fused_step()
    for i in range(WARMUP_STEPS):
        ts, rs, metrics = fused(ts, rs, chunk, prios, jax.random.key(i),
                                jnp.float32(0.4))
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for i in range(MEASURE_STEPS):
        ts, rs, metrics = fused(ts, rs, chunk, prios,
                                jax.random.key(100 + i), jnp.float32(0.4))
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    bps = MEASURE_STEPS / dt
    print(json.dumps({
        "metric": "learner_batches_per_sec_batch512_framepool_per_ingest",
        "value": round(bps, 2),
        "unit": "batches/s",
        "vs_baseline": round(bps / BASELINE_BPS, 2),
    }))


if __name__ == "__main__":
    main()
