"""Headline benchmark: learner throughput on one TPU chip + end-to-end rates.

Reference baseline: 10-12 batches/s at batch 512 on a V100 learner fed by a
separate replay server (``origin_repo/README.md:42``; BASELINE.md).  Part 1
measures the SAME unit of work, harder: each learner step here also ingests
512 fresh transitions and performs the PER priority write-back on-device —
work the reference offloads to its replay server — fused into one XLA
program on the Atari-shape DuelingDQN (84x84x4 uint8 stacks, batch 512),
repeated ``REPS`` times for a spread.

Part 2 runs the REAL concurrent pipeline (ApexTrainer + actor processes) to
measure the other half of the primary metric: env-frames/sec ingested and
learner-steps/sec sustained end to end — queue, staging, and publish
overhead included (the numpy env stands in for ALE, absent in this image).

Replay is the frame-pool layout: 2^19 transitions + 2^20 single frames
resident in HBM (~7.5GB/chip); an 8-chip slice with per-chip shards doubles
the reference's 2e6 total capacity.  Stacks are gathered on device at
sample time.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} plus
"spread" (min/max over reps) and "e2e" (the ApexTrainer rates).
vs_baseline = value / 11.0 (midpoint of the reference's 10-12 range).
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_BPS = 11.0
BATCH = 512
FRAME_SHAPE = (84, 84, 1)
FRAME_STACK = 4
CAPACITY = 2 ** 19
FRAME_CAPACITY = 2 ** 20
CHUNK = 512            # transitions ingested per fused step
CHUNK_FRAMES = 512 + 16
WARMUP_STEPS = 3
# env overrides let CI smoke-test the bench on CPU at toy scale; the
# driver's real-chip run uses the defaults
MEASURE_STEPS = int(os.environ.get("BENCH_STEPS", 50))
REPS = int(os.environ.get("BENCH_REPS", 3))
# first TPU compile of the concurrent pipeline eats ~20-40s of this wall
# budget; the steady-state window after it is what the sliding rate
# counters report
E2E_SECONDS = float(os.environ.get("BENCH_E2E_SECONDS", 90.0))


def _synthetic_chunk(rng: np.random.Generator) -> tuple[dict, np.ndarray]:
    """A representative actor chunk: CHUNK transitions over CHUNK_FRAMES
    contiguous frames, stacks referencing chunk-relative windows."""
    d = int(np.prod(FRAME_SHAPE))
    base = np.minimum(np.arange(CHUNK), CHUNK_FRAMES - 1 - 3)
    offs = np.arange(-(FRAME_STACK - 1), 1)
    obs_ref = np.maximum(base[:, None] + offs[None, :], 0).astype(np.int32)
    next_ref = np.minimum(obs_ref + 3, CHUNK_FRAMES - 1).astype(np.int32)
    chunk = dict(
        frames=rng.integers(0, 255, (CHUNK_FRAMES, d)).astype(np.uint8),
        n_frames=np.int32(CHUNK_FRAMES),
        n_trans=np.int32(CHUNK),
        action=rng.integers(0, 6, CHUNK).astype(np.int32),
        reward=rng.normal(size=CHUNK).astype(np.float32),
        discount=np.full(CHUNK, 0.99 ** 3, np.float32),
        obs_ref=obs_ref,
        next_ref=next_ref,
    )
    prios = np.abs(rng.normal(size=CHUNK)).astype(np.float32) + 1e-3
    return chunk, prios


def bench_fused_step() -> dict:
    """Part 1: the fused ingest+sample+update+write-back step, pre-staged
    device inputs, REPS timed repetitions."""
    from apex_tpu.models.dueling import DuelingDQN
    from apex_tpu.ops.losses import make_optimizer
    from apex_tpu.replay.frame_pool import FramePoolReplay
    from apex_tpu.training.learner import LearnerCore
    from apex_tpu.training.state import create_train_state

    model = DuelingDQN(num_actions=6)
    pool = FramePoolReplay(capacity=CAPACITY, frame_shape=FRAME_SHAPE,
                           frame_stack=FRAME_STACK,
                           frame_capacity=FRAME_CAPACITY)
    optimizer = make_optimizer()
    ts = create_train_state(
        model, optimizer, jax.random.key(0),
        jnp.zeros((1, 84, 84, FRAME_STACK), jnp.uint8))
    core = LearnerCore(apply_fn=model.apply, replay=pool,
                       optimizer=optimizer, batch_size=BATCH,
                       target_update_interval=2500)
    rs = pool.init()

    rng = np.random.default_rng(0)
    chunk, prios = _synthetic_chunk(rng)
    chunk = jax.device_put(chunk)
    prios = jax.device_put(jnp.asarray(prios))

    fused = core.jit_fused_step()
    for i in range(WARMUP_STEPS):
        ts, rs, metrics = fused(ts, rs, chunk, prios, jax.random.key(i),
                                jnp.float32(0.4))
    jax.block_until_ready(metrics["loss"])

    rates = []
    for rep in range(REPS):
        t0 = time.perf_counter()
        for i in range(MEASURE_STEPS):
            ts, rs, metrics = fused(ts, rs, chunk, prios,
                                    jax.random.key(1000 * rep + i),
                                    jnp.float32(0.4))
        jax.block_until_ready(metrics["loss"])
        rates.append(MEASURE_STEPS / (time.perf_counter() - t0))

    from apex_tpu.utils.profiling import DEFAULT_PEAK, flops_per_call, mfu
    flops = flops_per_call(fused, ts, rs, chunk, prios, jax.random.key(0),
                           jnp.float32(0.4))
    peak = (float(os.environ["BENCH_PEAK_TFLOPS"]) * 1e12
            if "BENCH_PEAK_TFLOPS" in os.environ else DEFAULT_PEAK)
    util = mfu(flops, float(np.median(rates)), peak)
    return {"median": float(np.median(rates)),
            "min": round(min(rates), 2), "max": round(max(rates), 2),
            "reps": REPS,
            "mfu": None if util is None else round(util, 4)}


def bench_end_to_end() -> dict:
    """Part 2: the real ApexTrainer pipeline — actor processes feeding the
    fused learner through the bounded queues — for E2E_SECONDS."""
    import dataclasses

    from apex_tpu.config import small_test_config
    from apex_tpu.training.apex import ApexTrainer

    n_actors, n_envs = 4, 8          # 32 ladder slots in 4 processes
    cfg = small_test_config(capacity=2 ** 14, batch_size=BATCH,
                            n_actors=n_actors)
    cfg = cfg.replace(
        learner=dataclasses.replace(cfg.learner, batch_size=BATCH,
                                    ingest_chunk=BATCH,
                                    compute_dtype="bfloat16"),
        replay=dataclasses.replace(cfg.replay, warmup=2048),
        actor=dataclasses.replace(cfg.actor, n_envs_per_actor=n_envs))
    trainer = ApexTrainer(cfg, publish_min_seconds=0.5)
    from apex_tpu.native.ring import ShmChunkQueue
    data_plane = ("shm" if isinstance(trainer.pool.chunk_queue,
                                      ShmChunkQueue) else "mp.Queue")
    t0 = time.monotonic()
    trainer.train(total_steps=10 ** 9, max_seconds=E2E_SECONDS,
                  log_every=10 ** 9)
    dt = time.monotonic() - t0
    # steady-state rates from the sliding tick windows — first-compile time
    # (~20-40s of the wall budget) would otherwise dominate the average
    return {"env_frames_per_sec": round(trainer.frames_rate.rate, 1),
            "learner_steps_per_sec": round(trainer.steps_rate.rate, 2),
            "transitions_per_sec":
                round(trainer.steps_rate.rate * BATCH, 1),
            "total_frames": trainer.ingested,
            "total_steps": trainer.steps_rate.total,
            "actors": n_actors, "envs_per_actor": n_envs,
            "data_plane": data_plane,
            "seconds": round(dt, 1)}


def main() -> None:
    # The fused step routes the frame gather through the pallas kernel on
    # TPU (ops/gather.py).  If the kernel ever fails to compile on a new
    # runtime, fall back to the XLA gather rather than losing the metric.
    try:
        fused = bench_fused_step()
        fused["gather"] = os.environ.get("APEX_GATHER_MODE", "auto")
    except Exception:
        os.environ["APEX_GATHER_MODE"] = "xla"
        fused = bench_fused_step()
        fused["gather"] = "xla-fallback"
    try:
        e2e = bench_end_to_end()
    except Exception as exc:      # never lose the primary metric
        e2e = {"error": f"{type(exc).__name__}: {exc}"}
    bps = fused["median"]
    print(json.dumps({
        "metric": "learner_batches_per_sec_batch512_framepool_per_ingest",
        "value": round(bps, 2),
        "unit": "batches/s",
        "vs_baseline": round(bps / BASELINE_BPS, 2),
        "spread": {"min": fused["min"], "max": fused["max"],
                   "reps": fused["reps"]},
        "mfu": fused["mfu"],
        "e2e": e2e,
    }))


if __name__ == "__main__":
    main()
