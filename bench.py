"""Headline benchmark: learner batches/sec on one TPU chip.

Reference baseline: 10-12 batches/s at batch 512 on a V100 learner fed by a
separate replay server (``origin_repo/README.md:42``; BASELINE.md).  We
measure the SAME unit of work, harder: each learner step here also ingests
512 fresh transitions and performs the PER priority write-back on-device —
work the reference offloads to its replay server — fused into one XLA
program on the Atari-shape DuelingDQN (84x84x4 uint8, batch 512, 2^20 PER
capacity).

Prints one JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = value / 11.0 (midpoint of the reference's 10-12 range).
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_BPS = 11.0
BATCH = 512
OBS_SHAPE = (84, 84, 4)
# Stacked-frame storage: obs+next_obs cost ~56KB/transition plus XLA tiling
# padding (84 -> 128 on the tiled minor dim), so 2^16 * ~86KB = 5.6GB fits
# v5e's 16GB HBM with headroom.  The frame-pool layout (one 84x84 frame
# stored once, stacks gathered by index) is what restores 2^20+ capacity.
CAPACITY = 2 ** 16
WARMUP_STEPS = 3
MEASURE_STEPS = 50


def main() -> None:
    from apex_tpu.models.dueling import DuelingDQN
    from apex_tpu.training.learner import build_learner

    model = DuelingDQN(num_actions=6)
    example_obs = jnp.zeros((1,) + OBS_SHAPE, jnp.uint8)
    core, ts, rs = build_learner(
        model, CAPACITY, example_obs, jax.random.key(0), batch_size=BATCH,
        target_update_interval=2500)

    rng = np.random.default_rng(0)
    host = dict(
        obs=rng.integers(0, 255, (BATCH,) + OBS_SHAPE).astype(np.uint8),
        action=rng.integers(0, 6, BATCH).astype(np.int32),
        reward=rng.normal(size=BATCH).astype(np.float32),
        next_obs=rng.integers(0, 255, (BATCH,) + OBS_SHAPE).astype(np.uint8),
        discount=np.full(BATCH, 0.99 ** 3, np.float32))
    ingest = jax.device_put(host)
    prios = jnp.ones(BATCH, jnp.float32)

    fused = core.jit_fused_step()
    # pre-fill past a warmup's worth so sampling has mass
    for i in range(WARMUP_STEPS):
        ts, rs, metrics = fused(ts, rs, ingest, prios, jax.random.key(i),
                                jnp.float32(0.4))
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for i in range(MEASURE_STEPS):
        ts, rs, metrics = fused(ts, rs, ingest, prios,
                                jax.random.key(100 + i), jnp.float32(0.4))
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    bps = MEASURE_STEPS / dt
    print(json.dumps({
        "metric": "learner_batches_per_sec_batch512_with_per_ingest",
        "value": round(bps, 2),
        "unit": "batches/s",
        "vs_baseline": round(bps / BASELINE_BPS, 2),
    }))


if __name__ == "__main__":
    main()
